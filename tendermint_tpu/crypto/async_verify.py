"""Asynchronous verification service: cross-caller micro-batching,
host/device pipelining, and a verified-signature cache.

Round-5 closed the kernel question (the per-signature program runs
within ~10-20% of the VPU's elementwise floor — ROUND5_NOTES.md §1), so
the next end-to-end win has to come from the dispatch pattern: every
device round trip costs ~45-120 ms through the tunnel, yet the hot
callers (VoteSet.add_votes slices, gossip prechecks, blocksync windows)
each construct their own BatchVerifier and submit batches that are
individually below the CPU/TPU breakeven — so no caller ever amortizes
a dispatch, even when several of them are verifying at the same moment.

This module is the continuous-batching answer (the Orca-style
iteration-level scheduling of inference serving, applied to signature
verification; PAPERS.md):

  * `submit(pub, msg, sig) -> Future[bool]` never blocks.  Requests
    from independent callers land in ONE submission queue; a daemon
    worker coalesces them into a single batch and dispatches when the
    queue reaches a size rung from the `_bucket` ladder or when a
    linger deadline (`TM_TPU_LINGER_MS`) expires.  Below-threshold
    flushes route to the host path exactly as today.
  * Double-buffered host/device pipelining: the worker ENQUEUES the
    compiled device program for batch i (JAX dispatch is async) and
    immediately starts host prep (sign-bytes SHA-512, s<L) for batch
    i+1; verdicts are drained when a second batch is in flight or the
    queue runs dry.  Batches over TM_TPU_CHUNK reuse the r5 chunk
    machinery (ops.ed25519_jax.chunks_of).
  * A bounded verified-signature LRU cache keyed by
    (pub, sha256(msg), sig) is consulted before enqueue and populated
    ONLY on success — gossip duplicates and replay re-verification
    never reach the device (and a corrupted signature can never be
    cached as valid, by construction).

Degradation contract (the `_DEVICE_READY` guarantee, one level up): the
worker only dispatches to the device after crypto.batch's warmup has
proven it answers; until then — and forever, on a wedged tunnel —
every flush runs the host path, so a submitter is never blocked by
backend init, compile-cache loads, or a hung transport.

Env knobs:
  TM_TPU_ASYNC_VERIFY   1 (default) routes the framework's verify
                        surfaces through the service; 0 restores
                        per-caller BatchVerifier instances.
  TM_TPU_LINGER_MS      coalescing window in milliseconds (default 1.0).
  TM_TPU_VERIFY_CACHE   verified-signature cache capacity in entries
                        (default 65536; 0 disables the cache).
  TM_TPU_MESH           multi-device dispatch (crypto/mesh_dispatch):
                        unset/auto shards large flushes across the full
                        device mesh and pins small ones to one chip;
                        1 forces single-device (bit-identical to the
                        pre-mesh service); 0 restores the legacy
                        synchronous multi-device routing.
  TM_TPU_MESH_MIN_SHARD flush size at/above which a flush shards
                        (default 64 rows per device).
  TM_TPU_TRACE          1 additionally records submit/coalesce/flush/
                        host-prep/device-execute spans into the
                        utils.trace ring (docs/observability.md); the
                        latency histograms below are always on.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future

from tendermint_tpu.utils import devmon as _devmon
from tendermint_tpu.utils import trace as _trace
from tendermint_tpu.utils.metrics import Histogram

from . import ed25519 as _ed
from . import batch as _batch
from . import mesh_dispatch as _mesh
from .batch import _pub_bytes, _split_verify

DEFAULT_LINGER_MS = 1.0
DEFAULT_CACHE_SIZE = 65536
MAX_COALESCE = 16384  # per-flush cap == the bucket ladder's top rung

# -- pipeline latency histograms (process-wide, like the service itself;
# node/metrics.py registers them so every node's /metrics scrape exposes
# them).  Buckets reach down to 50us: host flushes of small rungs finish
# well under the default prometheus grid.
_FAST_BUCKETS = (
    0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
    0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
)

VERIFY_QUEUE_WAIT_SECONDS = Histogram(
    "verify_queue_wait_seconds",
    "Time a request sits in the submission queue before its flush",
    namespace="tendermint", subsystem="crypto", buckets=_FAST_BUCKETS)
VERIFY_LINGER_SECONDS = Histogram(
    "verify_linger_seconds",
    "How long a flush lingered coalescing before dispatch",
    namespace="tendermint", subsystem="crypto", buckets=_FAST_BUCKETS)
VERIFY_HOST_PREP_SECONDS = Histogram(
    "verify_host_prep_seconds",
    "Host-side device-batch preparation (sign-bytes SHA-512, s<L, padding)",
    namespace="tendermint", subsystem="crypto", buckets=_FAST_BUCKETS)
VERIFY_DEVICE_EXECUTE_SECONDS = Histogram(
    "verify_device_execute_seconds",
    "Device enqueue to verdict readback per chunk, by bucket rung",
    namespace="tendermint", subsystem="crypto", label_names=("rung",),
    buckets=_FAST_BUCKETS)
VERIFY_E2E_SECONDS = Histogram(
    "verify_e2e_seconds",
    "Submit to resolve end to end, by resolution path",
    namespace="tendermint", subsystem="crypto", label_names=("path",),
    buckets=_FAST_BUCKETS)

PIPELINE_HISTOGRAMS = (
    VERIFY_QUEUE_WAIT_SECONDS,
    VERIFY_LINGER_SECONDS,
    VERIFY_HOST_PREP_SECONDS,
    VERIFY_DEVICE_EXECUTE_SECONDS,
    VERIFY_E2E_SECONDS,
)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return max(0, int(os.environ.get(name, default)))
    except ValueError:
        return default


class _Request:
    __slots__ = ("pub", "msg", "sig", "key", "future", "t_submit")

    def __init__(self, pub: bytes, msg: bytes, sig: bytes, key, future: Future,
                 t_submit: float):
        self.pub = pub
        self.msg = msg
        self.sig = sig
        self.key = key
        self.future = future
        self.t_submit = t_submit


class VerifiedSigCache:
    """Bounded thread-safe LRU of (pub, sha256(msg), sig) triples proven
    VALID.  Only True verdicts are ever stored: a rejected signature is
    re-verified on every appearance, so a corrupted signature cannot be
    cached as valid no matter what races occur."""

    def __init__(self, maxsize: int | None = None):
        # None = resolve TM_TPU_VERIFY_CACHE at every probe, so a value
        # set AFTER the process-wide service was built still takes
        # effect (the construction-time capture was half of the
        # order-dependent test_multinode flake — the pinned-threshold
        # half lives in crypto/batch.py)
        self._pinned_maxsize = maxsize
        self._d: OrderedDict = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    @property
    def maxsize(self) -> int:
        if self._pinned_maxsize is not None:
            return self._pinned_maxsize
        return _env_int("TM_TPU_VERIFY_CACHE", DEFAULT_CACHE_SIZE)

    @staticmethod
    def key(pub: bytes, msg: bytes, sig: bytes) -> tuple:
        return (pub, hashlib.sha256(msg).digest(), sig)

    def get(self, key) -> bool:
        if self.maxsize <= 0:
            self.misses += 1  # tmsan: shared=diagnostic counter on the disabled-cache path; tolerates lost updates
            return False
        with self._lock:
            if key in self._d:
                self._d.move_to_end(key)
                self.hits += 1
                return True
            self.misses += 1
            return False

    def put(self, key) -> None:
        if self.maxsize <= 0:
            return
        with self._lock:
            self._d[key] = True
            self._d.move_to_end(key)
            while len(self._d) > self.maxsize:
                self._d.popitem(last=False)

    def __len__(self) -> int:
        return len(self._d)


class VerifyService:
    """The process-wide verification daemon.  See the module docstring
    for the batching/pipelining/caching design; `get_service()` returns
    the shared instance."""

    def __init__(self, *, linger_ms: float | None = None,
                 cache_size: int | None = None,
                 cpu_threshold: int | None = None):
        # linger/cache sizing resolve their env knobs lazily when not
        # pinned by a ctor arg — see VerifiedSigCache.maxsize
        self._pinned_linger_ms = linger_ms
        self.cache = VerifiedSigCache(cache_size)
        self._cv = threading.Condition()
        self._queue: deque[_Request] = deque()
        self._worker: threading.Thread | None = None
        self._closed = False
        self.stats = {
            "submitted": 0,
            "flushes": 0,
            "host_flushes": 0,
            "device_batches": 0,
            "coalesced_max": 0,
            "pipelined_drains": 0,
            "mesh_pinned_batches": 0,
            "mesh_sharded_batches": 0,
        }
        # last (path, reason) the router chose — tests assert the
        # routing DECISION (pinned vs sharded), not just the verdicts
        self.last_route: tuple[str, str] | None = None  # tmsan: shared=atomic tuple rebind, last-write-wins diagnostic
        # the threshold/readiness arbitration reuses JAXBatchVerifier's
        # lazy measurement machinery; on a jax-less box every flush
        # routes to the host path
        try:
            self._jax_bv = _batch.JAXBatchVerifier(cpu_threshold=cpu_threshold)
        except Exception:  # noqa: BLE001 — no jax: host-only service
            self._jax_bv = None
        # AOT warm-on-start (ops/shape_plan, ISSUE 7): if an operator
        # ran `tendermint-tpu warm` (a saved plan exists next to the
        # compile cache), deserialize/compile its executables on a
        # daemon thread NOW so the first real flush finds warm programs
        # instead of paying the ~100 s relay inline.  Strict no-op
        # otherwise, and TM_TPU_AOT=0 kills it; a wedged tunnel wedges
        # only the warm thread (same contract as start_device_warmup).
        if self._jax_bv is not None:
            try:
                from tendermint_tpu.ops import shape_plan as _sp

                _sp.start_background_warm("verify-service-start")
            except Exception:  # noqa: BLE001 — warm is best-effort
                pass

    @property
    def linger_s(self) -> float:
        ms = (self._pinned_linger_ms if self._pinned_linger_ms is not None
              else _env_float("TM_TPU_LINGER_MS", DEFAULT_LINGER_MS))
        return ms / 1e3

    # -- submission (caller side; never blocks) -----------------------

    def submit(self, pub, msg: bytes, sig: bytes) -> Future:
        """Queue one verification; resolves to bool.  Cache hits resolve
        immediately without queueing."""
        return self.submit_many([(pub, msg, sig)])[0]

    def submit_many(self, items) -> list[Future]:
        """Bulk submit: one cache pass + one queue append under a single
        lock acquisition — the large-batch path (a 10k commit) must not
        pay per-item lock traffic."""
        t_sub = time.perf_counter()  # one stamp per bulk submit, not per item
        futures: list[Future] = []
        fresh: list[_Request] = []
        for pub, msg, sig in items:
            pub_b = _pub_bytes(pub)
            msg_b = bytes(msg)
            sig_b = bytes(sig)
            key = VerifiedSigCache.key(pub_b, msg_b, sig_b)
            fut: Future = Future()
            futures.append(fut)
            if self.cache.get(key):
                fut.set_result(True)
                VERIFY_E2E_SECONDS.observe(time.perf_counter() - t_sub,
                                           path="cache")
            else:
                fresh.append(_Request(pub_b, msg_b, sig_b, key, fut, t_sub))
        if fresh:
            with self._cv:
                if self._closed:
                    raise RuntimeError("verify service is closed")
                self.stats["submitted"] += len(fresh)
                self._queue.extend(fresh)
                self._ensure_worker_locked()
                self._cv.notify()
        if _trace.enabled():
            _trace.record("verify.submit", t_sub,
                          time.perf_counter() - t_sub,
                          n=len(futures), fresh=len(fresh))
        return futures

    def verify_many(self, items) -> list[bool]:
        """Sync convenience wrapper: submit all, wait for all.  Blocks
        only on verification work the host path could also perform —
        never on device warmup (the worker routes around a cold or
        wedged device)."""
        futs = self.submit_many(items)
        return [bool(f.result()) for f in futs]

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    # -- worker -------------------------------------------------------

    def _ensure_worker_locked(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._run, daemon=True, name="tm-verify-service")
            self._worker.start()

    def _flush_rung(self) -> int:
        """Stop lingering once the queue can fill a device-worthy bucket:
        the smallest `_bucket` rung at/above the dispatch threshold (64
        while the threshold is unmeasured or on a host-only service)."""
        target = 64
        bv = self._jax_bv
        if bv is not None:
            thr = bv.cpu_threshold
            if thr is None:
                thr = _batch.measured_cpu_threshold_ready()
            if thr is not None:
                target = max(64, min(MAX_COALESCE, thr))
        try:
            from tendermint_tpu.ops.ed25519_jax import _bucket

            return min(MAX_COALESCE, _bucket(target))
        except Exception:  # noqa: BLE001
            return target

    def _collect(self, block: bool) -> list[_Request]:
        """Take the next coalesced batch off the queue: wait (if `block`)
        for the first request, then linger until the rung fills or the
        deadline passes."""
        with self._cv:
            if block:
                while not self._queue and not self._closed:
                    self._cv.wait()
            if not self._queue:
                return []
            t_linger0 = time.perf_counter()
            if self.linger_s > 0:
                rung = self._flush_rung()
                deadline = time.monotonic() + self.linger_s
                while (len(self._queue) < rung and not self._closed):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
            batch = [self._queue.popleft()
                     for _ in range(min(len(self._queue), MAX_COALESCE))]
            # counter updates stay inside the lock so service_stats()
            # snapshots are never torn across a flush boundary
            self.stats["flushes"] += 1
            self.stats["coalesced_max"] = max(self.stats["coalesced_max"],
                                              len(batch))
        now = time.perf_counter()
        VERIFY_LINGER_SECONDS.observe(now - t_linger0)
        for r in batch:
            VERIFY_QUEUE_WAIT_SECONDS.observe(now - r.t_submit)
        if _trace.enabled():
            _trace.record("verify.coalesce", t_linger0, now - t_linger0,
                          n=len(batch))
        return batch

    def _run(self) -> None:
        # in-flight device batches awaiting verdict readback:
        # (pending_device_value, reqs).  Depth 2 = double buffering —
        # batch i executes on device while batch i+1 is host-prepped and
        # enqueued behind it.
        inflight: deque = deque()
        while True:
            with self._cv:
                if self._closed and not self._queue and not inflight:
                    return
                queue_empty = not self._queue
            if inflight and queue_empty:
                self._drain_one(inflight)
                continue
            reqs = self._collect(block=not inflight)
            if reqs:
                try:
                    self._flush(reqs, inflight)
                except BaseException as e:  # noqa: BLE001
                    self._resolve_failed(reqs, e)
            while len(inflight) >= 2:
                self._drain_one(inflight)

    def _flush(self, reqs: list[_Request], inflight: deque) -> None:
        """Route one coalesced batch: host below threshold / before
        device readiness; async device enqueue otherwise.  The flush
        span records which path won and WHY (the question the raw
        counters could never answer)."""
        t0 = time.perf_counter()
        path, reason = self._route(reqs, inflight)
        self.last_route = (path, reason)  # tmsan: shared=atomic tuple rebind, last-write-wins diagnostic
        if _trace.enabled():
            _trace.record("verify.flush", t0, time.perf_counter() - t0,
                          path=path, reason=reason, n=len(reqs))

    def _route(self, reqs: list[_Request], inflight: deque) -> tuple[str, str]:
        n = len(reqs)
        bv = self._jax_bv
        if bv is None:
            self._host_verify(reqs)
            return "host", "no_jax"
        thr = bv._resolved_threshold(n)
        if n < thr:
            self._host_verify(reqs)
            return "host", "below_threshold"
        if not _batch._DEVICE_READY.is_set():
            # identical degradation to JAXBatchVerifier._ed_batch: kick
            # the warmup worker, verify on host meanwhile — a wedged
            # tunnel must never block a submitter
            _batch.start_device_warmup()
            self._host_verify(reqs)
            return "host", "device_not_ready"
        mixed = any(len(r.pub) != 32 for r in reqs)
        if mixed or os.environ.get("TM_TPU_RLC", "0") == "1":
            # rarer shapes (secp-mixed batches, RLC) run the existing
            # synchronous routing — bit-identical verdicts, no pipelining
            self._sync_device_verify(reqs, bv)
            return "device", "sync_routing"
        ndev = bv._device_count()
        if ndev > 1:
            if not _mesh.dispatcher_enabled():
                # TM_TPU_MESH=0: legacy synchronous mesh routing
                self._sync_device_verify(reqs, bv)
                return "device", "sync_routing"
            route, m = _mesh.decide(n, ndev)
            if route == "sharded":
                try:
                    self._enqueue_sharded(reqs, inflight, m)
                    return "device", "mesh_sharded"
                except Exception:  # noqa: BLE001 — mesh hiccup: host
                    self._host_verify(reqs)
                    return "host", "device_error"
            # pinned: fall through to the single-chip pipelined enqueue
            # below — identical programs/cache keys to a 1-device node
        try:
            self._enqueue_device(reqs, inflight)
            if ndev > 1:
                with self._cv:
                    self.stats["mesh_pinned_batches"] += 1
                return "device", "mesh_pinned"
            return "device", "pipelined"
        except Exception:  # noqa: BLE001 — device hiccup: host fallback
            self._host_verify(reqs)
            return "host", "device_error"

    def _enqueue_device(self, reqs: list[_Request], inflight: deque) -> None:
        """Host prep + async enqueue of the per-row device program,
        chunked via the r5 machinery when TM_TPU_CHUNK is set.  Verdict
        readback happens in _drain_one — by then the worker has already
        host-prepped the NEXT batch behind the executing one."""
        from tendermint_tpu.ops import ed25519_jax as dev

        n = len(reqs)
        impl = dev.default_impl()
        base_mxu = dev._resolve_optin(impl)
        chunk = dev._chunk_size()
        plan = (dev.chunks_of(n, chunk) if chunk and n > chunk
                else [(0, n, dev._bucket(n))])
        for start, end, b in plan:
            sub = reqs[start:end]
            t_prep = time.perf_counter()
            rows = dev.prepare_batch([r.pub for r in sub],
                                     [r.msg for r in sub],
                                     [r.sig for r in sub])
            padded = dev._pad_rows(end - start, b, *rows)
            prep_dt = time.perf_counter() - t_prep
            VERIFY_HOST_PREP_SECONDS.observe(prep_dt)
            if _trace.enabled():
                _trace.record("verify.host_prep", t_prep, prep_dt,
                              n=end - start, rung=b)
            if _devmon.STATS.enabled:
                _mesh.record_pinned_flush(
                    end - start, b, nbytes=sum(a.nbytes for a in padded))
            while len(inflight) >= 2:
                self._drain_one(inflight)
            t_enq = time.perf_counter()
            pending = dev._compiled(b, impl, base_mxu)(*padded)
            inflight.append((pending, sub, t_enq, b))
            with self._cv:
                self.stats["device_batches"] += 1

    def _enqueue_sharded(self, reqs: list[_Request], inflight: deque,
                         m: int) -> None:
        """Host prep + async enqueue of the SHARDED per-row program over
        an m-device mesh: rows are padded to a device-multiple rung and
        pre-partitioned (jax.device_put against the mesh NamedSharding)
        so XLA never reshards.  Readback stays in _drain_one — the
        double-buffered pipeline is preserved across the mesh hop."""
        from tendermint_tpu.ops import ed25519_jax as dev
        from tendermint_tpu.parallel import sharding as _sh

        mesh = _mesh.mesh_for(m)
        n = len(reqs)
        b = _sh.sharded_bucket(n, m)
        t_prep = time.perf_counter()
        rows = dev.prepare_batch([r.pub for r in reqs],
                                 [r.msg for r in reqs],
                                 [r.sig for r in reqs])
        padded = dev._pad_rows(n, b, *rows)
        prep_dt = time.perf_counter() - t_prep
        VERIFY_HOST_PREP_SECONDS.observe(prep_dt)
        if _trace.enabled():
            _trace.record("verify.host_prep", t_prep, prep_dt,
                          n=n, rung=b)
        if _devmon.STATS.enabled:
            _mesh.record_sharded_flush(
                n, b, mesh, nbytes=sum(a.nbytes for a in padded))
        while len(inflight) >= 2:
            self._drain_one(inflight)
        t_enq = time.perf_counter()
        pending = _mesh.enqueue_sharded(mesh, padded)
        inflight.append((pending, reqs, t_enq, b))
        with self._cv:
            self.stats["device_batches"] += 1
            self.stats["mesh_sharded_batches"] += 1

    def _drain_one(self, inflight: deque) -> None:
        import numpy as np

        pending, reqs, t_enq, rung = inflight.popleft()
        with self._cv:
            self.stats["pipelined_drains"] += 1
        try:
            oks = np.asarray(pending)[:len(reqs)]
        except Exception:  # noqa: BLE001 — readback failed: host verdicts
            self._host_verify(reqs, count_flush=False)
            return
        dt = time.perf_counter() - t_enq
        VERIFY_DEVICE_EXECUTE_SECONDS.observe(dt, rung=rung)
        if _trace.enabled():
            # enqueue-to-readback: includes time queued behind the other
            # in-flight batch, i.e. what a submitter actually experiences
            _trace.record("verify.device_execute", t_enq, dt,
                          n=len(reqs), rung=rung)
        self._resolve(reqs, oks, path="device")

    def _sync_device_verify(self, reqs: list[_Request], bv) -> None:
        t0 = time.perf_counter()
        try:
            oks = _split_verify([r.pub for r in reqs],
                                [r.msg for r in reqs],
                                [r.sig for r in reqs], bv._ed_batch)
            with self._cv:
                self.stats["device_batches"] += 1
        except Exception:  # noqa: BLE001
            self._host_verify(reqs)
            return
        dt = time.perf_counter() - t0
        VERIFY_DEVICE_EXECUTE_SECONDS.observe(dt, rung="sync")
        if _trace.enabled():
            _trace.record("verify.device_execute", t0, dt,
                          n=len(reqs), rung="sync")
        self._resolve(reqs, oks, path="device")

    def _host_verify(self, reqs: list[_Request], count_flush: bool = True) -> None:
        if count_flush:
            with self._cv:
                self.stats["host_flushes"] += 1
        t0 = time.perf_counter()
        try:
            oks = _split_verify([r.pub for r in reqs],
                                [r.msg for r in reqs],
                                [r.sig for r in reqs],
                                _ed.verify_batch_fast)
        except BaseException as e:  # noqa: BLE001
            self._resolve_failed(reqs, e)
            return
        if _trace.enabled():
            _trace.record("verify.host_verify", t0,
                          time.perf_counter() - t0, n=len(reqs))
        self._resolve(reqs, oks, path="host")

    def _resolve(self, reqs: list[_Request], oks, path: str = "host") -> None:
        now = time.perf_counter()
        for req, ok in zip(reqs, oks):
            ok = bool(ok)
            if ok:
                self.cache.put(req.key)
            VERIFY_E2E_SECONDS.observe(now - req.t_submit, path=path)
            req.future.set_result(ok)

    def _resolve_failed(self, reqs: list[_Request], err: BaseException) -> None:
        """Catastrophic path: even the batched host verify raised.  Fall
        back to per-item verification so one poisoned row cannot take
        the whole flush down; anything still failing propagates the
        error to its submitter (same contract as the sync path, which
        would have raised to the caller)."""
        for req in reqs:
            try:
                ok = bool(_ed.verify_fast(req.pub, req.msg, req.sig))
                if ok:
                    self.cache.put(req.key)
                req.future.set_result(ok)
            except BaseException:  # noqa: BLE001
                req.future.set_exception(err)


class ServiceBatchVerifier:
    """BatchVerifier-protocol adapter over the shared service: existing
    call sites keep their add/count/verify shape, but the actual crypto
    is submitted to the cross-caller queue — concurrent verifiers'
    batches coalesce into one device dispatch, and duplicates resolve
    from the verified-signature cache."""

    def __init__(self, service: "VerifyService | None" = None):
        self._svc = service or get_service()
        self._items: list[tuple[bytes, bytes, bytes]] = []

    def add(self, pub_key, msg: bytes, sig: bytes) -> None:
        self._items.append((_pub_bytes(pub_key), bytes(msg), bytes(sig)))

    def count(self) -> int:
        return len(self._items)

    def verify(self) -> tuple[bool, list[bool]]:
        items, self._items = self._items, []
        if not items:
            return False, []
        oks = self._svc.verify_many(items)
        return all(oks), oks


# ---------------------------------------------------------------------------
# Process-wide singleton
# ---------------------------------------------------------------------------

_SERVICE: VerifyService | None = None
_SERVICE_LOCK = threading.Lock()


def service_enabled() -> bool:
    """TM_TPU_ASYNC_VERIFY gates the routing of the framework's verify
    surfaces through the service (default on); resolved per call so
    tests/benches can flip it."""
    return os.environ.get("TM_TPU_ASYNC_VERIFY", "1") != "0"


def get_service() -> VerifyService:
    global _SERVICE
    svc = _SERVICE
    if svc is not None:
        return svc
    with _SERVICE_LOCK:
        if _SERVICE is None:
            _SERVICE = VerifyService()
        return _SERVICE


def reset_service(**kwargs) -> VerifyService:
    """Replace the singleton (tests/benchmarks): closes the old worker
    and builds a fresh service with the given constructor overrides."""
    global _SERVICE
    with _SERVICE_LOCK:
        if _SERVICE is not None:
            _SERVICE.close()
        _SERVICE = VerifyService(**kwargs)
        return _SERVICE


def clear_service() -> None:
    """Drop the singleton entirely so the NEXT get_service() rebuilds it
    from the then-current environment.  Test isolation: the service
    captures TM_TPU_CPU_THRESHOLD / linger / cache sizing at
    construction, so a singleton built by an earlier test would silently
    override a later test's env (the order-dependent multinode
    device-path flake)."""
    global _SERVICE
    with _SERVICE_LOCK:
        if _SERVICE is not None:
            _SERVICE.close()
        _SERVICE = None


def verify_many(items) -> list[bool]:
    """Module-level sync wrapper over the shared service."""
    return get_service().verify_many(items)


def submit(pub, msg: bytes, sig: bytes) -> Future:
    return get_service().submit(pub, msg, sig)


def verify_one(pub, msg: bytes, sig: bytes, fill: bool = True) -> bool:
    """ONE signature against the shared verified-sig LRU: probe, else
    verify on the caller's thread and (only on success, and only when
    `fill`) populate the cache.  The single-signature admission paths
    (VoteSet.add_vote for own/broadcast-delivered votes, proposal
    signature checks) were the last verify surfaces still paying a full
    scalar-mult per CALLER per signature — an in-process multi-node net
    (simnet, test localnets) re-verified every broadcast vote once per
    node.  Deliberately NOT submitted to the service queue: a single
    must not perturb the worker's flush/coalescing behavior (threshold
    routing, linger) nor block on the linger window — the cache is the
    only shared state touched.

    `fill=False` (the vote path) probes without populating: votes are
    ALSO verified through the batched service path (precheck slices),
    and a cache pre-filled by trickling singles would starve those
    flushes of fresh work — the device batch path would never engage on
    a quiet net.  Slice-verified votes fill the cache through the
    service as before; the probe here then serves every later caller.
    TM_TPU_ASYNC_VERIFY=0 keeps even the cache out of the path."""
    if not service_enabled():
        return bool(pub.verify_signature(msg, sig))
    cache = get_service().cache
    key = VerifiedSigCache.key(_pub_bytes(pub), bytes(msg), bytes(sig))
    if cache.get(key):
        return True
    ok = bool(pub.verify_signature(msg, sig))
    if ok and fill:
        cache.put(key)
    return ok


def service_stats() -> dict:
    """Counters for metrics/bench scraping; zeros before first use (the
    metrics server must not instantiate the service).  The service
    counters are snapshotted under the service lock and the cache
    counters under the cache lock, so a scrape never observes a torn
    counter set (e.g. a flush counted but its coalesced_max not yet)."""
    svc = _SERVICE
    if svc is None:
        return {"submitted": 0, "flushes": 0, "host_flushes": 0,
                "device_batches": 0, "coalesced_max": 0,
                "pipelined_drains": 0, "mesh_pinned_batches": 0,
                "mesh_sharded_batches": 0, "cache_hits": 0,
                "cache_misses": 0, "cache_size": 0, "queue_depth": 0}
    with svc._cv:
        out = dict(svc.stats)
        out["queue_depth"] = len(svc._queue)
    cache = svc.cache
    with cache._lock:
        out["cache_hits"] = cache.hits
        out["cache_misses"] = cache.misses
        out["cache_size"] = len(cache._d)
    return out


def device_stats() -> dict:
    """Device-layer snapshot next to service_stats(): utils/devmon's
    compile/occupancy/padding/memory accounting folded together with the
    service's live queue depth and verified-signature cache hit ratio —
    one call answers "how efficiently is the device being used right
    now".  Like service_stats(), never instantiates the service."""
    out = _devmon.device_stats()
    st = service_stats()
    lookups = st["cache_hits"] + st["cache_misses"]
    out["queue_depth"] = st["queue_depth"]
    out["cache_hit_ratio"] = (round(st["cache_hits"] / lookups, 6)
                              if lookups else 0.0)
    return out


def new_service_batch_verifier():
    """A BatchVerifier routed through the shared service when enabled,
    else a plain per-caller verifier — THE constructor every verify
    surface (vote slices, commit windows, evidence) should use."""
    if service_enabled():
        return ServiceBatchVerifier()
    return _batch.new_batch_verifier()
