"""Asynchronous verification service: cross-caller micro-batching,
host/device pipelining, and a verified-signature cache.

Round-5 closed the kernel question (the per-signature program runs
within ~10-20% of the VPU's elementwise floor — ROUND5_NOTES.md §1), so
the next end-to-end win has to come from the dispatch pattern: every
device round trip costs ~45-120 ms through the tunnel, yet the hot
callers (VoteSet.add_votes slices, gossip prechecks, blocksync windows)
each construct their own BatchVerifier and submit batches that are
individually below the CPU/TPU breakeven — so no caller ever amortizes
a dispatch, even when several of them are verifying at the same moment.

This module is the continuous-batching answer (the Orca-style
iteration-level scheduling of inference serving, applied to signature
verification; PAPERS.md):

  * `submit(pub, msg, sig) -> Future[bool]` never blocks.  Requests
    from independent callers land in ONE submission queue; a daemon
    worker coalesces them into a single batch and dispatches when the
    queue reaches a size rung from the `_bucket` ladder or when a
    linger deadline (`TM_TPU_LINGER_MS`) expires.  Below-threshold
    flushes route to the host path exactly as today.
  * Double-buffered host/device pipelining: the worker ENQUEUES the
    compiled device program for batch i (JAX dispatch is async) and
    immediately starts host prep (sign-bytes SHA-512, s<L) for batch
    i+1; verdicts are drained when a second batch is in flight or the
    queue runs dry.  Batches over TM_TPU_CHUNK reuse the r5 chunk
    machinery (ops.ed25519_jax.chunks_of).
  * A bounded verified-signature LRU cache keyed by
    (pub, sha256(msg), sig) is consulted before enqueue and populated
    ONLY on success — gossip duplicates and replay re-verification
    never reach the device (and a corrupted signature can never be
    cached as valid, by construction).

Degradation contract (the `_DEVICE_READY` guarantee, one level up): the
worker only dispatches to the device after crypto.batch's warmup has
proven it answers; until then — and forever, on a wedged tunnel —
every flush runs the host path, so a submitter is never blocked by
backend init, compile-cache loads, or a hung transport.

Env knobs:
  TM_TPU_ASYNC_VERIFY   1 (default) routes the framework's verify
                        surfaces through the service; 0 restores
                        per-caller BatchVerifier instances.
  TM_TPU_LINGER_MS      coalescing window in milliseconds (default 1.0).
  TM_TPU_VERIFY_CACHE   verified-signature cache capacity in entries
                        (default 65536; 0 disables the cache).
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict, deque
from concurrent.futures import Future

from . import ed25519 as _ed
from . import batch as _batch
from .batch import _pub_bytes, _split_verify

DEFAULT_LINGER_MS = 1.0
DEFAULT_CACHE_SIZE = 65536
MAX_COALESCE = 16384  # per-flush cap == the bucket ladder's top rung


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return max(0, int(os.environ.get(name, default)))
    except ValueError:
        return default


class _Request:
    __slots__ = ("pub", "msg", "sig", "key", "future")

    def __init__(self, pub: bytes, msg: bytes, sig: bytes, key, future: Future):
        self.pub = pub
        self.msg = msg
        self.sig = sig
        self.key = key
        self.future = future


class VerifiedSigCache:
    """Bounded thread-safe LRU of (pub, sha256(msg), sig) triples proven
    VALID.  Only True verdicts are ever stored: a rejected signature is
    re-verified on every appearance, so a corrupted signature cannot be
    cached as valid no matter what races occur."""

    def __init__(self, maxsize: int):
        self.maxsize = maxsize
        self._d: OrderedDict = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    @staticmethod
    def key(pub: bytes, msg: bytes, sig: bytes) -> tuple:
        return (pub, hashlib.sha256(msg).digest(), sig)

    def get(self, key) -> bool:
        if self.maxsize <= 0:
            self.misses += 1
            return False
        with self._lock:
            if key in self._d:
                self._d.move_to_end(key)
                self.hits += 1
                return True
            self.misses += 1
            return False

    def put(self, key) -> None:
        if self.maxsize <= 0:
            return
        with self._lock:
            self._d[key] = True
            self._d.move_to_end(key)
            while len(self._d) > self.maxsize:
                self._d.popitem(last=False)

    def __len__(self) -> int:
        return len(self._d)


class VerifyService:
    """The process-wide verification daemon.  See the module docstring
    for the batching/pipelining/caching design; `get_service()` returns
    the shared instance."""

    def __init__(self, *, linger_ms: float | None = None,
                 cache_size: int | None = None,
                 cpu_threshold: int | None = None):
        self.linger_s = (linger_ms if linger_ms is not None
                         else _env_float("TM_TPU_LINGER_MS",
                                         DEFAULT_LINGER_MS)) / 1e3
        self.cache = VerifiedSigCache(
            cache_size if cache_size is not None
            else _env_int("TM_TPU_VERIFY_CACHE", DEFAULT_CACHE_SIZE))
        self._cv = threading.Condition()
        self._queue: deque[_Request] = deque()
        self._worker: threading.Thread | None = None
        self._closed = False
        self.stats = {
            "submitted": 0,
            "flushes": 0,
            "host_flushes": 0,
            "device_batches": 0,
            "coalesced_max": 0,
            "pipelined_drains": 0,
        }
        # the threshold/readiness arbitration reuses JAXBatchVerifier's
        # lazy measurement machinery; on a jax-less box every flush
        # routes to the host path
        try:
            self._jax_bv = _batch.JAXBatchVerifier(cpu_threshold=cpu_threshold)
        except Exception:  # noqa: BLE001 — no jax: host-only service
            self._jax_bv = None

    # -- submission (caller side; never blocks) -----------------------

    def submit(self, pub, msg: bytes, sig: bytes) -> Future:
        """Queue one verification; resolves to bool.  Cache hits resolve
        immediately without queueing."""
        return self.submit_many([(pub, msg, sig)])[0]

    def submit_many(self, items) -> list[Future]:
        """Bulk submit: one cache pass + one queue append under a single
        lock acquisition — the large-batch path (a 10k commit) must not
        pay per-item lock traffic."""
        futures: list[Future] = []
        fresh: list[_Request] = []
        for pub, msg, sig in items:
            pub_b = _pub_bytes(pub)
            msg_b = bytes(msg)
            sig_b = bytes(sig)
            key = VerifiedSigCache.key(pub_b, msg_b, sig_b)
            fut: Future = Future()
            futures.append(fut)
            if self.cache.get(key):
                fut.set_result(True)
            else:
                fresh.append(_Request(pub_b, msg_b, sig_b, key, fut))
        if fresh:
            with self._cv:
                if self._closed:
                    raise RuntimeError("verify service is closed")
                self.stats["submitted"] += len(fresh)
                self._queue.extend(fresh)
                self._ensure_worker_locked()
                self._cv.notify()
        return futures

    def verify_many(self, items) -> list[bool]:
        """Sync convenience wrapper: submit all, wait for all.  Blocks
        only on verification work the host path could also perform —
        never on device warmup (the worker routes around a cold or
        wedged device)."""
        futs = self.submit_many(items)
        return [bool(f.result()) for f in futs]

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    # -- worker -------------------------------------------------------

    def _ensure_worker_locked(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._run, daemon=True, name="tm-verify-service")
            self._worker.start()

    def _flush_rung(self) -> int:
        """Stop lingering once the queue can fill a device-worthy bucket:
        the smallest `_bucket` rung at/above the dispatch threshold (64
        while the threshold is unmeasured or on a host-only service)."""
        target = 64
        bv = self._jax_bv
        if bv is not None:
            thr = bv.cpu_threshold
            if thr is None:
                thr = _batch.measured_cpu_threshold_ready()
            if thr is not None:
                target = max(64, min(MAX_COALESCE, thr))
        try:
            from tendermint_tpu.ops.ed25519_jax import _bucket

            return min(MAX_COALESCE, _bucket(target))
        except Exception:  # noqa: BLE001
            return target

    def _collect(self, block: bool) -> list[_Request]:
        """Take the next coalesced batch off the queue: wait (if `block`)
        for the first request, then linger until the rung fills or the
        deadline passes."""
        import time

        with self._cv:
            if block:
                while not self._queue and not self._closed:
                    self._cv.wait()
            if not self._queue:
                return []
            if self.linger_s > 0:
                rung = self._flush_rung()
                deadline = time.monotonic() + self.linger_s
                while (len(self._queue) < rung and not self._closed):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
            batch = [self._queue.popleft()
                     for _ in range(min(len(self._queue), MAX_COALESCE))]
        self.stats["flushes"] += 1
        self.stats["coalesced_max"] = max(self.stats["coalesced_max"],
                                          len(batch))
        return batch

    def _run(self) -> None:
        # in-flight device batches awaiting verdict readback:
        # (pending_device_value, reqs).  Depth 2 = double buffering —
        # batch i executes on device while batch i+1 is host-prepped and
        # enqueued behind it.
        inflight: deque = deque()
        while True:
            with self._cv:
                if self._closed and not self._queue and not inflight:
                    return
                queue_empty = not self._queue
            if inflight and queue_empty:
                self._drain_one(inflight)
                continue
            reqs = self._collect(block=not inflight)
            if reqs:
                try:
                    self._flush(reqs, inflight)
                except BaseException as e:  # noqa: BLE001
                    self._resolve_failed(reqs, e)
            while len(inflight) >= 2:
                self._drain_one(inflight)

    def _flush(self, reqs: list[_Request], inflight: deque) -> None:
        """Route one coalesced batch: host below threshold / before
        device readiness; async device enqueue otherwise."""
        n = len(reqs)
        bv = self._jax_bv
        if bv is None:
            self._host_verify(reqs)
            return
        thr = bv._resolved_threshold(n)
        if n < thr:
            self._host_verify(reqs)
            return
        if not _batch._DEVICE_READY.is_set():
            # identical degradation to JAXBatchVerifier._ed_batch: kick
            # the warmup worker, verify on host meanwhile — a wedged
            # tunnel must never block a submitter
            _batch.start_device_warmup()
            self._host_verify(reqs)
            return
        mixed = any(len(r.pub) != 32 for r in reqs)
        if mixed or bv._device_count() > 1 or \
                os.environ.get("TM_TPU_RLC", "0") == "1":
            # rarer shapes (secp-mixed batches, mesh sharding, RLC) run
            # the existing synchronous routing — bit-identical verdicts,
            # no pipelining
            self._sync_device_verify(reqs, bv)
            return
        try:
            self._enqueue_device(reqs, inflight)
        except Exception:  # noqa: BLE001 — device hiccup: host fallback
            self._host_verify(reqs)

    def _enqueue_device(self, reqs: list[_Request], inflight: deque) -> None:
        """Host prep + async enqueue of the per-row device program,
        chunked via the r5 machinery when TM_TPU_CHUNK is set.  Verdict
        readback happens in _drain_one — by then the worker has already
        host-prepped the NEXT batch behind the executing one."""
        from tendermint_tpu.ops import ed25519_jax as dev

        n = len(reqs)
        impl = dev.default_impl()
        base_mxu = dev._resolve_optin(impl)
        chunk = dev._chunk_size()
        plan = (dev.chunks_of(n, chunk) if chunk and n > chunk
                else [(0, n, dev._bucket(n))])
        for start, end, b in plan:
            sub = reqs[start:end]
            rows = dev.prepare_batch([r.pub for r in sub],
                                     [r.msg for r in sub],
                                     [r.sig for r in sub])
            padded = dev._pad_rows(end - start, b, *rows)
            while len(inflight) >= 2:
                self._drain_one(inflight)
            pending = dev._compiled(b, impl, base_mxu)(*padded)
            inflight.append((pending, sub))
            self.stats["device_batches"] += 1

    def _drain_one(self, inflight: deque) -> None:
        import numpy as np

        pending, reqs = inflight.popleft()
        self.stats["pipelined_drains"] += 1
        try:
            oks = np.asarray(pending)[:len(reqs)]
        except Exception:  # noqa: BLE001 — readback failed: host verdicts
            self._host_verify(reqs, count_flush=False)
            return
        self._resolve(reqs, oks)

    def _sync_device_verify(self, reqs: list[_Request], bv) -> None:
        try:
            oks = _split_verify([r.pub for r in reqs],
                                [r.msg for r in reqs],
                                [r.sig for r in reqs], bv._ed_batch)
            self.stats["device_batches"] += 1
        except Exception:  # noqa: BLE001
            self._host_verify(reqs)
            return
        self._resolve(reqs, oks)

    def _host_verify(self, reqs: list[_Request], count_flush: bool = True) -> None:
        if count_flush:
            self.stats["host_flushes"] += 1
        try:
            oks = _split_verify([r.pub for r in reqs],
                                [r.msg for r in reqs],
                                [r.sig for r in reqs],
                                _ed.verify_batch_fast)
        except BaseException as e:  # noqa: BLE001
            self._resolve_failed(reqs, e)
            return
        self._resolve(reqs, oks)

    def _resolve(self, reqs: list[_Request], oks) -> None:
        for req, ok in zip(reqs, oks):
            ok = bool(ok)
            if ok:
                self.cache.put(req.key)
            req.future.set_result(ok)

    def _resolve_failed(self, reqs: list[_Request], err: BaseException) -> None:
        """Catastrophic path: even the batched host verify raised.  Fall
        back to per-item verification so one poisoned row cannot take
        the whole flush down; anything still failing propagates the
        error to its submitter (same contract as the sync path, which
        would have raised to the caller)."""
        for req in reqs:
            try:
                ok = bool(_ed.verify_fast(req.pub, req.msg, req.sig))
                if ok:
                    self.cache.put(req.key)
                req.future.set_result(ok)
            except BaseException:  # noqa: BLE001
                req.future.set_exception(err)


class ServiceBatchVerifier:
    """BatchVerifier-protocol adapter over the shared service: existing
    call sites keep their add/count/verify shape, but the actual crypto
    is submitted to the cross-caller queue — concurrent verifiers'
    batches coalesce into one device dispatch, and duplicates resolve
    from the verified-signature cache."""

    def __init__(self, service: "VerifyService | None" = None):
        self._svc = service or get_service()
        self._items: list[tuple[bytes, bytes, bytes]] = []

    def add(self, pub_key, msg: bytes, sig: bytes) -> None:
        self._items.append((_pub_bytes(pub_key), bytes(msg), bytes(sig)))

    def count(self) -> int:
        return len(self._items)

    def verify(self) -> tuple[bool, list[bool]]:
        items, self._items = self._items, []
        if not items:
            return False, []
        oks = self._svc.verify_many(items)
        return all(oks), oks


# ---------------------------------------------------------------------------
# Process-wide singleton
# ---------------------------------------------------------------------------

_SERVICE: VerifyService | None = None
_SERVICE_LOCK = threading.Lock()


def service_enabled() -> bool:
    """TM_TPU_ASYNC_VERIFY gates the routing of the framework's verify
    surfaces through the service (default on); resolved per call so
    tests/benches can flip it."""
    return os.environ.get("TM_TPU_ASYNC_VERIFY", "1") != "0"


def get_service() -> VerifyService:
    global _SERVICE
    svc = _SERVICE
    if svc is not None:
        return svc
    with _SERVICE_LOCK:
        if _SERVICE is None:
            _SERVICE = VerifyService()
        return _SERVICE


def reset_service(**kwargs) -> VerifyService:
    """Replace the singleton (tests/benchmarks): closes the old worker
    and builds a fresh service with the given constructor overrides."""
    global _SERVICE
    with _SERVICE_LOCK:
        if _SERVICE is not None:
            _SERVICE.close()
        _SERVICE = VerifyService(**kwargs)
        return _SERVICE


def verify_many(items) -> list[bool]:
    """Module-level sync wrapper over the shared service."""
    return get_service().verify_many(items)


def submit(pub, msg: bytes, sig: bytes) -> Future:
    return get_service().submit(pub, msg, sig)


def service_stats() -> dict:
    """Counters for metrics/bench scraping; zeros before first use (the
    metrics server must not instantiate the service)."""
    svc = _SERVICE
    if svc is None:
        return {"submitted": 0, "flushes": 0, "host_flushes": 0,
                "device_batches": 0, "coalesced_max": 0,
                "pipelined_drains": 0, "cache_hits": 0, "cache_misses": 0,
                "cache_size": 0}
    out = dict(svc.stats)
    out["cache_hits"] = svc.cache.hits
    out["cache_misses"] = svc.cache.misses
    out["cache_size"] = len(svc.cache)
    return out


def new_service_batch_verifier():
    """A BatchVerifier routed through the shared service when enabled,
    else a plain per-caller verifier — THE constructor every verify
    surface (vote slices, commit windows, evidence) should use."""
    if service_enabled():
        return ServiceBatchVerifier()
    return _batch.new_batch_verifier()
