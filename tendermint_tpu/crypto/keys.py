"""Ed25519 key objects (Go-style 64-byte private key = seed || pubkey).

Parity target: reference crypto/ed25519/ed25519.go (PrivKey/PubKey, address =
SHA-256(pubkey)[:20] via tmhash.SumTruncated) and crypto/crypto.go:22-41.

Signing uses libcrypto (`cryptography`) when available — it produces the same
deterministic RFC 8032 signatures as the pure-Python path (asserted in tests);
verification defaults to the ZIP-215 reference verifier, with batch paths
going through crypto.batch / ops.ed25519_jax.
"""

from __future__ import annotations

import functools
import secrets

from . import ed25519 as _ed
from . import tmhash

try:  # fast path: libcrypto signing
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey as _CPriv,
    )

    _HAVE_LIBCRYPTO = True
except Exception:  # pragma: no cover
    _HAVE_LIBCRYPTO = False

KEY_TYPE = "ed25519"
PUB_KEY_SIZE = 32
PRIV_KEY_SIZE = 64
SIGNATURE_SIZE = 64


class PubKey:
    __slots__ = ("_bytes",)

    def __init__(self, data: bytes):
        if len(data) != PUB_KEY_SIZE:
            raise ValueError(f"ed25519 pubkey must be {PUB_KEY_SIZE} bytes")
        self._bytes = bytes(data)

    def bytes_(self) -> bytes:
        return self._bytes

    @property
    def data(self) -> bytes:
        return self._bytes

    def address(self) -> bytes:
        return tmhash.sum_truncated(self._bytes)

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        # libcrypto fast path with pure-ZIP-215 fallback on rejection —
        # verdicts bit-identical to _ed.verify (see verify_fast)
        return _ed.verify_fast(self._bytes, msg, sig)

    def type(self) -> str:
        return KEY_TYPE

    def __eq__(self, other) -> bool:
        return isinstance(other, PubKey) and other._bytes == self._bytes

    def __hash__(self) -> int:
        return hash(self._bytes)

    def __repr__(self) -> str:
        return f"PubKey(ed25519:{self._bytes.hex()[:16]}…)"


class PrivKey:
    __slots__ = ("_seed", "_pub", "_csigner")

    def __init__(self, data: bytes):
        """Accepts a 64-byte Go-style key (seed||pub) or a 32-byte seed."""
        if len(data) == PRIV_KEY_SIZE:
            seed = data[:32]
        elif len(data) == 32:
            seed = data
        else:
            raise ValueError("ed25519 privkey must be 32 or 64 bytes")
        self._seed = bytes(seed)
        if _HAVE_LIBCRYPTO:
            self._csigner = _CPriv.from_private_bytes(self._seed)
            pub = self._csigner.public_key().public_bytes_raw()
        else:
            self._csigner = None
            pub = _ed.pubkey_from_seed(self._seed)
        self._pub = pub
        if len(data) == PRIV_KEY_SIZE and data[32:] != pub:
            raise ValueError("privkey pubkey suffix mismatch")

    def bytes_(self) -> bytes:
        return self._seed + self._pub

    @property
    def data(self) -> bytes:
        return self.bytes_()

    def sign(self, msg: bytes) -> bytes:
        if self._csigner is not None:
            return self._csigner.sign(msg)
        return _ed.sign(self._seed, msg)

    def pub_key(self) -> PubKey:
        return PubKey(self._pub)

    def type(self) -> str:
        return KEY_TYPE

    def __eq__(self, other) -> bool:
        return isinstance(other, PrivKey) and other.bytes_() == self.bytes_()


def gen_priv_key() -> PrivKey:
    return PrivKey(secrets.token_bytes(32))


@functools.lru_cache(maxsize=16384)
def priv_key_from_seed(seed: bytes) -> PrivKey:
    """Seed -> key, memoized: construction derives the public key (a
    full scalar mult — milliseconds on the pure-python path), keys are
    immutable, and deterministic harnesses (simnet genesis, testnets)
    re-derive the same thousands of slot keys every run."""
    return PrivKey(seed)
