"""Native batched canonical sign-bytes assembly (jax-free wrapper).

Binds src/native/edhost.cpp's `tmed_batch_sign_bytes`: one C call emits
every delimited canonical precommit row for a commit (~40 ns/row vs
~4 µs/row for the Python template path — 0.4 ms vs 40 ms on a 10k
commit).  Lives under crypto/ (not ops/) so the types layer can use it
without importing the jax-backed ops package.
"""

from __future__ import annotations

import ctypes
import threading

import numpy as np

from tendermint_tpu.utils.native_loader import load_native_lib

_LIB_NAME = "libedhost.so"
_lock = threading.Lock()
_lib = None
_failed = False


def _load():
    global _lib, _failed
    with _lock:
        if _lib is not None or _failed:
            return _lib
        lib = load_native_lib(_LIB_NAME, "edhost", required=False)
        if lib is None or not hasattr(lib, "tmed_batch_sign_bytes"):
            _failed = True
            return None
        lib.tmed_batch_sign_bytes.argtypes = [
            ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.tmed_batch_sign_bytes.restype = ctypes.c_uint64
        _lib = lib
        return _lib


def batch_sign_bytes(prefix_block: bytes, prefix_nil: bytes, suffix: bytes,
                     flags, ts_ns) -> tuple[bytes, np.ndarray] | None:
    """(buffer, offsets[n+1]) of delimited rows, or None when the native
    kernel is unavailable (callers fall back to the Python template).
    flags: per-row truthy = COMMIT prefix; ts_ns: per-row int64."""
    lib = _load()
    if lib is None:
        return None
    n = len(ts_ns)
    NS = 1_000_000_000
    # split in Python: divmod is exact for timestamps beyond the
    # int64-nanosecond range (Go's zero time is ~-6.2e19 ns)
    secs = np.empty(n, dtype=np.int64)
    nanos = np.empty(n, dtype=np.int32)
    for i, t in enumerate(ts_ns):
        s, nan = divmod(t, NS)
        # wrap into int64 two's complement exactly like the Python
        # path's encode_varint_signed: adversarially decoded timestamps
        # (seconds=2^63-1 with nanos >= 1e9) push s past int64 and must
        # produce the same bytes — and a clean bad-signature rejection —
        # not an OverflowError out of the verify path
        secs[i] = ((s + (1 << 63)) % (1 << 64)) - (1 << 63)
        nanos[i] = nan
    flags_arr = np.ascontiguousarray(np.asarray(flags, dtype=np.uint8))
    cap = n * (max(len(prefix_block), len(prefix_nil)) + len(suffix) + 40) + 16
    out = np.zeros(cap, dtype=np.uint8)
    offsets = np.zeros(n + 1, dtype=np.uint64)
    total = lib.tmed_batch_sign_bytes(
        ctypes.c_uint64(n),
        prefix_block, ctypes.c_uint64(len(prefix_block)),
        prefix_nil, ctypes.c_uint64(len(prefix_nil)),
        suffix, ctypes.c_uint64(len(suffix)),
        flags_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        secs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        nanos.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.c_uint64(cap),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
    )
    if total == 0:
        return None
    return out[:total].tobytes(), offsets
