from .tmhash import sum_sha256, sum_truncated, ADDRESS_SIZE
from .keys import PrivKey, PubKey, gen_priv_key, priv_key_from_seed
from .batch import BatchVerifier, CPUBatchVerifier, new_batch_verifier
from .async_verify import (  # noqa: F401 — the async service surface
    ServiceBatchVerifier,
    VerifyService,
    get_service,
    new_service_batch_verifier,
    service_stats,
)
