"""TCP transport: SecretConnection + NodeInfo handshake + channel framing.

Parity: reference p2p/transport_mconn.go (Transport iface {Listen,
Accept, Dial}) layered over p2p/conn/secret_connection.go, plus the
NodeInfo compatibility handshake (p2p/node_info.go:51-74: protocol
versions, network/chain-id, supported channels, moniker) and the
dialed-peer identity check (dialed NodeID must match the authenticated
key's address, p2p/transport.go).

Framing inside the encrypted stream: 1-byte channel id + payload per
sealed message — the prioritized multiplexing the reference does in
MConnection lives in the Router's per-peer priority queue instead
(SURVEY §2.6), so this layer stays a plain ordered pipe.

Addresses use the reference's `NodeID@host:port` format
(p2p/netaddress.go:419).
"""

from __future__ import annotations

import asyncio
import json

from tendermint_tpu.utils.log import Logger, nop_logger

from .secret_connection import HandshakeError, SecretConnection
from .types import NodeID, node_id_from_pubkey

P2P_PROTOCOL_VERSION = 8  # reference version/version.go:11-24
BLOCK_PROTOCOL_VERSION = 11


def parse_net_address(addr: str) -> tuple[NodeID, str, int]:
    """`nodeid@host:port` → (node_id, host, port)."""
    node_id, _, hostport = addr.partition("@")
    if not hostport:
        raise ValueError(f"address {addr!r} missing @host:port")
    if hostport.startswith("["):
        host, _, rest = hostport[1:].partition("]")
        port = rest.lstrip(":")
    else:
        host, _, port = hostport.rpartition(":")
    if not host or not port:
        raise ValueError(f"address {addr!r} missing host or port")
    return node_id.lower(), host, int(port)


class TCPConnection:
    """One authenticated peer connection (channel frames over a
    SecretConnection)."""

    def __init__(self, sconn: SecretConnection, writer, remote_id: NodeID,
                 remote_node_info: dict, on_close=None,
                 send_limiter=None, recv_limiter=None):
        from tendermint_tpu.utils.flowrate import NopLimiter

        self._sconn = sconn
        self._writer = writer
        self.remote_id = remote_id
        self.remote_node_info = remote_node_info
        self._closed = False
        self._send_lock = asyncio.Lock()
        self._on_close = on_close
        self._send_limiter = send_limiter or NopLimiter()
        self._recv_limiter = recv_limiter or NopLimiter()
        # plaintext frame bytes through this connection (payload + the
        # 1-byte channel tag; SecretConnection sealing overhead excluded)
        # — the transport-level view behind the router's per-channel
        # counters, surfaced in net_info peer snapshots
        self.bytes_sent = 0
        self.bytes_received = 0

    async def send(self, channel_id: int, data: bytes) -> None:
        if self._closed:
            raise ConnectionError("connection closed")
        try:
            async with self._send_lock:
                await self._send_limiter.limit(len(data) + 1)
                await self._sconn.send(bytes([channel_id]) + data)
                self.bytes_sent += len(data) + 1
        except (OSError, asyncio.IncompleteReadError) as e:
            raise ConnectionError(str(e)) from None

    async def receive(self) -> tuple[int, bytes]:
        if self._closed:
            raise ConnectionError("connection closed")
        try:
            msg = await self._sconn.receive()
        except (OSError, asyncio.IncompleteReadError) as e:
            raise ConnectionError(str(e)) from None
        if not msg:
            raise ConnectionError("empty frame")
        await self._recv_limiter.limit(len(msg))
        self.bytes_received += len(msg)
        return msg[0], msg[1:]

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._on_close is not None:
            self._on_close()
        try:
            self._writer.close()
            await self._writer.wait_closed()
        except Exception:
            pass


class TCPTransport:
    """Listening endpoint + dialer. Use `await listen()` before handing
    to the Router; register peer addresses with `add_peer_address` so
    `dial(node_id)` can resolve them."""

    def __init__(self, node_key, network: str, host: str = "0.0.0.0",
                 port: int = 26656, moniker: str = "", channels: bytes = b"",
                 logger: Logger | None = None,
                 max_incoming_connections: int = 64,
                 send_rate: int = 0, recv_rate: int = 0):
        self.node_key = node_key
        self.network = network
        self.host = host
        self.port = port
        self.moniker = moniker
        self.channels = channels
        self.logger = logger or nop_logger()
        self.max_incoming_connections = max_incoming_connections
        self.send_rate = send_rate  # bytes/sec per peer, 0 = unlimited
        self.recv_rate = recv_rate
        self.node_id: NodeID = node_key.node_id
        self.listen_addr: tuple[str, int] | None = None
        self._server: asyncio.AbstractServer | None = None
        self._accept_q: asyncio.Queue = asyncio.Queue(maxsize=64)
        self._addrs: dict[NodeID, tuple[str, int]] = {}
        self._incoming = 0
        self._closed = False

    # -- address book ----------------------------------------------------
    def add_peer_address(self, addr: str) -> NodeID:
        node_id, host, port = parse_net_address(addr)
        self._addrs[node_id] = (host, port)
        return node_id

    # -- node info handshake ---------------------------------------------
    def _node_info(self) -> dict:
        return {
            "protocol_version": {
                "p2p": P2P_PROTOCOL_VERSION,
                "block": BLOCK_PROTOCOL_VERSION,
            },
            "node_id": self.node_id,
            "network": self.network,
            "moniker": self.moniker,
            "channels": self.channels.hex(),
            "listen_port": self.listen_addr[1] if self.listen_addr else 0,
        }

    def _check_compat(self, info: dict) -> None:
        """Reference node_info.go CompatibleWith: same network, same p2p
        major, ≥1 common channel."""
        if info.get("network") != self.network:
            raise HandshakeError(
                f"peer network {info.get('network')!r} != ours {self.network!r}"
            )
        if info.get("protocol_version", {}).get("p2p") != P2P_PROTOCOL_VERSION:
            raise HandshakeError("incompatible p2p protocol version")
        ours, theirs = set(self.channels), set(bytes.fromhex(info.get("channels", "")))
        if ours and theirs and not (ours & theirs):
            raise HandshakeError("no common channels")

    async def _upgrade(self, reader, writer, expect_id: NodeID | None,
                       on_close=None) -> TCPConnection:
        return await asyncio.wait_for(
            self._upgrade_inner(reader, writer, expect_id, on_close), 15.0
        )

    async def _upgrade_inner(self, reader, writer, expect_id: NodeID | None,
                             on_close) -> TCPConnection:
        sconn = await SecretConnection.handshake(reader, writer, self.node_key.priv_key)
        remote_id = node_id_from_pubkey(sconn.remote_pub)
        if remote_id == self.node_id:
            raise HandshakeError("self-connection")
        if expect_id is not None and remote_id != expect_id:
            # dialed-peer auth: the key that signed must be the ID we dialed
            raise HandshakeError(f"dialed {expect_id[:8]} but peer is {remote_id[:8]}")
        await sconn.send(json.dumps(self._node_info()).encode())
        try:
            info = json.loads(await sconn.receive())
        except (json.JSONDecodeError, UnicodeDecodeError):
            raise HandshakeError("bad node info") from None
        self._check_compat(info)
        if info.get("node_id") != remote_id:
            raise HandshakeError("node info id does not match authenticated key")
        from tendermint_tpu.utils.flowrate import RateLimiter

        return TCPConnection(
            sconn, writer, remote_id, info, on_close=on_close,
            send_limiter=RateLimiter(self.send_rate) if self.send_rate else None,
            recv_limiter=RateLimiter(self.recv_rate) if self.recv_rate else None,
        )

    # -- transport interface ---------------------------------------------
    async def listen(self) -> tuple[str, int]:
        self._server = await asyncio.start_server(self._on_accept, self.host, self.port)
        self.listen_addr = self._server.sockets[0].getsockname()[:2]
        self.logger.info("p2p listening",
                         addr=f"{self.listen_addr[0]}:{self.listen_addr[1]}")
        return self.listen_addr

    async def _on_accept(self, reader, writer) -> None:
        if self._closed or self._incoming >= self.max_incoming_connections:
            writer.close()
            return
        self._incoming += 1

        def _dec():
            self._incoming -= 1

        try:
            conn = await self._upgrade(reader, writer, expect_id=None, on_close=_dec)
        except (HandshakeError, ConnectionError, asyncio.IncompleteReadError,
                asyncio.TimeoutError, OSError) as e:
            self.logger.info("inbound handshake failed", err=str(e))
            _dec()
            writer.close()
            return
        await self._accept_q.put(conn)

    async def accept(self) -> TCPConnection:
        conn = await self._accept_q.get()
        if conn is None:
            raise ConnectionError("transport closed")
        return conn

    async def dial(self, remote: NodeID | str, connect_timeout: float = 5.0) -> TCPConnection:
        if "@" in remote:
            remote = self.add_peer_address(remote)
        addr = self._addrs.get(remote)
        if addr is None:
            raise ConnectionError(f"no known address for peer {remote[:8]}")
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(*addr), connect_timeout
            )
        except asyncio.TimeoutError:
            raise ConnectionError(f"connect to {addr[0]}:{addr[1]} timed out") from None
        try:
            return await self._upgrade(reader, writer, expect_id=remote)
        except BaseException:
            writer.close()
            raise

    async def close(self) -> None:
        self._closed = True
        if self._server is not None:
            self._server.close()
            self._server = None
        try:
            self._accept_q.put_nowait(None)
        except asyncio.QueueFull:
            pass
