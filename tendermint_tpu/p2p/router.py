"""Router: moves Envelopes between local reactor channels and peer
connections.

Parity: reference p2p/router.go:15-525 — the new-architecture router the
reference prototyped but never wired (SURVEY §1); here it IS the
production stack.  Per peer: one recv task (frames → decode → channel
in-queues) and one send task (priority queue → frames); per channel: one
route task (out-queue → peer queues) and one error task (peer errors →
disconnect).  Peer lifecycle changes are published to subscribers
(reference PeerUpdates), which is how reactors learn to start/stop
per-peer gossip.
"""

from __future__ import annotations

import asyncio
import itertools

from tendermint_tpu.utils.log import Logger, nop_logger

from .channel import Channel
from .types import ChannelDescriptor, Envelope, NodeID, PeerStatus, PeerUpdate


class _Peer:
    def __init__(self, node_id: NodeID, conn):
        self.node_id = node_id
        self.conn = conn
        # (negated priority, seq) orders the heap: higher priority first,
        # FIFO within a priority class (reference mconn channel priorities)
        self.send_q: asyncio.PriorityQueue = asyncio.PriorityQueue(maxsize=4096)
        self.tasks: list[asyncio.Task] = []


class Router:
    def __init__(self, node_id: NodeID, transport, logger: Logger | None = None):
        self.node_id = node_id
        self.transport = transport
        self.logger = logger or nop_logger()
        self.channels: dict[int, Channel] = {}
        self.peers: dict[NodeID, _Peer] = {}
        self._peer_update_subs: list[asyncio.Queue] = []
        self._tasks: list[asyncio.Task] = []
        self._seq = itertools.count()
        self._stopping = False
        # per-channel traffic counters (reference p2p/metrics.go bytes
        # by channel), read by the metrics scraper
        self.bytes_received: dict[int, int] = {}
        self.bytes_sent: dict[int, int] = {}

    # -- channels --------------------------------------------------------
    def open_channel(self, descriptor: ChannelDescriptor) -> Channel:
        if descriptor.channel_id in self.channels:
            raise ValueError(f"channel {descriptor.channel_id:#x} already open")
        ch = Channel(descriptor)
        self.channels[descriptor.channel_id] = ch
        return ch

    # -- peer updates ----------------------------------------------------
    def subscribe_peer_updates(self) -> asyncio.Queue:
        q: asyncio.Queue[PeerUpdate] = asyncio.Queue(maxsize=256)
        self._peer_update_subs.append(q)
        return q

    def _publish_peer_update(self, update: PeerUpdate) -> None:
        for q in self._peer_update_subs:
            try:
                q.put_nowait(update)
            except asyncio.QueueFull:
                self.logger.error("peer update subscriber overflowed")

    def peer_ids(self) -> list[NodeID]:
        return list(self.peers.keys())

    # -- lifecycle -------------------------------------------------------
    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._tasks.append(loop.create_task(self._accept_loop()))
        for ch in self.channels.values():
            self._tasks.append(loop.create_task(self._route_channel(ch)))
            self._tasks.append(loop.create_task(self._route_errors(ch)))

    async def stop(self) -> None:
        self._stopping = True
        for peer in list(self.peers.values()):
            await self._disconnect(peer.node_id, notify=False)
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        await self.transport.close()

    # -- dialing/accepting ------------------------------------------------
    async def dial(self, remote_id: NodeID) -> None:
        if remote_id in self.peers or remote_id == self.node_id:
            return
        conn = await self.transport.dial(remote_id)
        # simultaneous dial+accept of the same peer: the check above ran
        # before the await — if the inbound side won, keep it
        if remote_id in self.peers:
            await conn.close()
            return
        self._add_peer(remote_id, conn)

    async def _accept_loop(self) -> None:
        while not self._stopping:
            try:
                conn = await self.transport.accept()
            except (ConnectionError, asyncio.CancelledError):
                return
            remote_id = conn.remote_id
            if remote_id in self.peers:
                await conn.close()
                continue
            self._add_peer(remote_id, conn)

    def _add_peer(self, node_id: NodeID, conn) -> None:
        assert node_id not in self.peers, f"duplicate peer {node_id[:8]}"
        peer = _Peer(node_id, conn)
        loop = asyncio.get_running_loop()
        peer.tasks.append(loop.create_task(self._peer_recv(peer)))
        peer.tasks.append(loop.create_task(self._peer_send(peer)))
        self.peers[node_id] = peer
        self.logger.info("peer up", peer=node_id[:8])
        self._publish_peer_update(PeerUpdate(node_id, PeerStatus.UP))

    async def disconnect(self, node_id: NodeID) -> None:
        """Drop a peer deliberately (seed-mode hangup, operator action)."""
        await self._disconnect(node_id)

    async def _disconnect(self, node_id: NodeID, notify: bool = True) -> None:
        peer = self.peers.pop(node_id, None)
        if peer is None:
            return
        await peer.conn.close()
        for t in peer.tasks:
            t.cancel()
        self.logger.info("peer down", peer=node_id[:8])
        if notify:
            self._publish_peer_update(PeerUpdate(node_id, PeerStatus.DOWN))

    # -- per-peer tasks ----------------------------------------------------
    async def _peer_recv(self, peer: _Peer) -> None:
        try:
            while True:
                channel_id, data = await peer.conn.receive()
                self.bytes_received[channel_id] = (
                    self.bytes_received.get(channel_id, 0) + len(data)
                )
                ch = self.channels.get(channel_id)
                if ch is None:
                    continue  # unknown channel: drop silently
                if len(data) > ch.descriptor.max_msg_bytes:
                    raise ValueError(f"oversized message on channel {channel_id:#x}")
                try:
                    msg = ch.descriptor.decode(data)
                except Exception as e:
                    raise ValueError(f"undecodable message: {e}")
                await ch.in_queue.put(
                    Envelope(message=msg, from_=peer.node_id, channel_id=channel_id)
                )
        except asyncio.CancelledError:
            return
        except (ConnectionError, Exception) as e:
            if not self._stopping and peer.node_id in self.peers:
                self.logger.info("peer recv ended", peer=peer.node_id[:8], err=str(e))
                asyncio.get_running_loop().create_task(self._disconnect(peer.node_id))

    async def _peer_send(self, peer: _Peer) -> None:
        try:
            while True:
                _, _, channel_id, data = await peer.send_q.get()
                await peer.conn.send(channel_id, data)
                self.bytes_sent[channel_id] = (
                    self.bytes_sent.get(channel_id, 0) + len(data)
                )
        except asyncio.CancelledError:
            return
        except ConnectionError:
            if not self._stopping and peer.node_id in self.peers:
                asyncio.get_running_loop().create_task(self._disconnect(peer.node_id))

    # -- channel routing ----------------------------------------------------
    async def _route_channel(self, ch: Channel) -> None:
        """Drain a channel's out-queue into peer send queues."""
        prio = -ch.descriptor.priority
        while True:
            try:
                env = await ch.out_queue.get()
            except asyncio.CancelledError:
                return
            data = ch.descriptor.encode(env.message)
            if env.broadcast:
                targets = [p for pid, p in self.peers.items() if pid != env.from_]
            else:
                p = self.peers.get(env.to)
                targets = [p] if p is not None else []
            for p in targets:
                try:
                    p.send_q.put_nowait((prio, next(self._seq), ch.channel_id, data))
                except asyncio.QueueFull:
                    # backpressure: drop lowest-urgency gossip rather than
                    # stall the whole channel (reference TrySend semantics)
                    self.logger.debug("peer send queue full", peer=p.node_id[:8])

    async def _route_errors(self, ch: Channel) -> None:
        while True:
            try:
                perr = await ch.err_queue.get()
            except asyncio.CancelledError:
                return
            self.logger.info("peer error", peer=perr.node_id[:8], err=perr.err)
            await self._disconnect(perr.node_id)
