"""Router: moves Envelopes between local reactor channels and peer
connections.

Parity: reference p2p/router.go:15-525 — the new-architecture router the
reference prototyped but never wired (SURVEY §1); here it IS the
production stack.  Per peer: one recv task (frames → decode → channel
in-queues), one send task (per-channel bounded queues drained by
weighted-fair scheduling), and one keepalive task (ping/pong liveness,
reference p2p/conn/connection.go:47-48); per channel: one route task
(out-queue → peer queues) and one error task (peer errors → disconnect).
Peer lifecycle changes are published to subscribers (reference
PeerUpdates), which is how reactors learn to start/stop per-peer gossip.

Send scheduling (reference MConnection sendRoutine,
p2p/conn/connection.go:422-434 sendSomePacketMsgs/channel selection):
each channel gets its OWN bounded queue per peer (descriptor
send_queue_capacity), so a saturating low-priority transfer (blocksync
block parts) can never crowd votes out of a shared queue; the send task
picks the non-empty channel with the lowest recently-sent/priority
ratio, which converges to priority-weighted bandwidth shares while
keeping every channel live.

Keepalive (reference ping/pong, connection.go:47-48,170-180): a ping
control frame every ping_interval; ANY inbound frame counts as life
(pong included); a peer silent for pong_timeout after a ping is evicted
— the Router publishes DOWN and the node's persistent-peer dialer
redials with backoff.
"""

from __future__ import annotations

import asyncio
from collections import deque

from tendermint_tpu.utils import clock as _clock
from tendermint_tpu.utils.log import Logger, nop_logger

from .channel import Channel
from .types import ChannelDescriptor, Envelope, NodeID, PeerStatus, PeerUpdate

# Control channel for router-internal keepalive frames.  Reserved: no
# reactor channel may claim it (reference puts ping/pong at the packet
# layer inside MConnection; here the frame layer is channel-tagged, so a
# reserved id is the equivalent).
CTRL_CHANNEL = 0xFE
_PING = b"\x01"
_PONG = b"\x02"


class _Peer:
    def __init__(self, node_id: NodeID, conn):
        self.node_id = node_id
        self.conn = conn
        self.connected_at = _clock.monotonic()
        # per-channel bounded send queues (reference MConnection
        # Channel.sendQueue w/ SendQueueCapacity): channel isolation is
        # the point — see module docstring
        self.send_queues: dict[int, deque] = {}
        # exponentially-decayed bytes recently sent per channel, the
        # fair-scheduling signal (reference channel.recentlySent)
        self.recent_sent: dict[int, float] = {}
        self._recent_stamp = _clock.monotonic()
        self.send_ready = asyncio.Event()
        self.pong_owed = False
        self.ping_due = False
        self.last_recv = _clock.monotonic()
        self.tasks: list[asyncio.Task] = []


class Router:
    def __init__(
        self,
        node_id: NodeID,
        transport,
        logger: Logger | None = None,
        ping_interval: float = 60.0,
        pong_timeout: float = 45.0,
    ):
        self.node_id = node_id
        self.transport = transport
        self.logger = logger or nop_logger()
        # reference defaults: pingInterval 60s / pongTimeout 45s
        # (p2p/conn/connection.go:47-48); tests shrink both
        self.ping_interval = ping_interval
        self.pong_timeout = pong_timeout
        self.channels: dict[int, Channel] = {}
        self.peers: dict[NodeID, _Peer] = {}
        self._peer_update_subs: list[asyncio.Queue] = []
        self._tasks: list[asyncio.Task] = []
        self._stopping = False
        # per-channel traffic counters (reference p2p/metrics.go bytes
        # by channel), read by the metrics scraper
        self.bytes_received: dict[int, int] = {}
        self.bytes_sent: dict[int, int] = {}
        # per-peer per-channel counters (reference p2p/metrics.go
        # PeerReceiveBytesTotal / PeerSendBytesTotal{peer_id, chID}) —
        # cumulative, so a peer's series survives its disconnect exactly
        # like a Prometheus counter's labelset would
        self.peer_bytes_received: dict[NodeID, dict[int, int]] = {}
        self.peer_bytes_sent: dict[NodeID, dict[int, int]] = {}
        # decoded inbound messages by concrete type (reference
        # MessageReceiveBytesTotal{message_type}, counted here as messages)
        self.msg_recv_count: dict[str, int] = {}
        # peer lifecycle counters (reference NumPeers is a gauge; the
        # connect/disconnect totals make churn visible after the fact)
        self.peers_connected = 0
        self.peers_disconnected = 0

    # -- channels --------------------------------------------------------
    def open_channel(self, descriptor: ChannelDescriptor) -> Channel:
        if descriptor.channel_id == CTRL_CHANNEL:
            raise ValueError(f"channel {CTRL_CHANNEL:#x} is reserved for keepalive")
        if descriptor.channel_id in self.channels:
            raise ValueError(f"channel {descriptor.channel_id:#x} already open")
        ch = Channel(descriptor)
        self.channels[descriptor.channel_id] = ch
        return ch

    # -- peer updates ----------------------------------------------------
    def subscribe_peer_updates(self) -> asyncio.Queue:
        q: asyncio.Queue[PeerUpdate] = asyncio.Queue(maxsize=256)
        self._peer_update_subs.append(q)
        return q

    def _publish_peer_update(self, update: PeerUpdate) -> None:
        for q in self._peer_update_subs:
            try:
                q.put_nowait(update)
            except asyncio.QueueFull:
                self.logger.error("peer update subscriber overflowed")

    def peer_ids(self) -> list[NodeID]:
        return list(self.peers.keys())

    # -- per-peer observability ------------------------------------------
    def send_queue_depths(self) -> list[tuple[NodeID, int, int]]:
        """(peer_id, channel_id, queued msgs) for every live per-peer
        per-channel send queue — the backpressure picture at a glance."""
        out = []
        for pid, peer in list(self.peers.items()):
            for cid, q in list(peer.send_queues.items()):
                out.append((pid, cid, len(q)))
        return out

    def peer_snapshot(self, node_id: NodeID) -> dict | None:
        """One peer's traffic/queue state for net_info (reference
        ConnectionStatus in p2p/conn/connection.go Status()).  Returns
        None for unknown peers; byte counters come from the cumulative
        per-peer dicts so they are exact even mid-transfer."""
        peer = self.peers.get(node_id)
        if peer is None:
            return None
        now = _clock.monotonic()
        recv = self.peer_bytes_received.get(node_id, {})
        sent = self.peer_bytes_sent.get(node_id, {})
        channels = []
        for cid in sorted(set(recv) | set(sent) | set(peer.send_queues)):
            channels.append({
                "ch_id": f"{cid:#x}",
                "send_queue_size": len(peer.send_queues.get(cid, ())),
                "recv_bytes": recv.get(cid, 0),
                "send_bytes": sent.get(cid, 0),
            })
        snap = {
            "duration_s": round(now - peer.connected_at, 3),
            "last_recv_age_s": round(now - peer.last_recv, 3),
            "channels": channels,
        }
        conn_sent = getattr(peer.conn, "bytes_sent", None)
        if conn_sent is not None:
            # TCP transport: raw frame bytes incl. the channel tag
            snap["conn_bytes_sent"] = conn_sent
            snap["conn_bytes_received"] = getattr(peer.conn, "bytes_received", 0)
        return snap

    # -- lifecycle -------------------------------------------------------
    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._tasks.append(loop.create_task(self._accept_loop()))
        for ch in self.channels.values():
            self._tasks.append(loop.create_task(self._route_channel(ch)))
            self._tasks.append(loop.create_task(self._route_errors(ch)))

    async def stop(self) -> None:
        self._stopping = True
        for peer in list(self.peers.values()):
            await self._disconnect(peer.node_id, notify=False)
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        await self.transport.close()

    # -- dialing/accepting ------------------------------------------------
    async def dial(self, remote_id: NodeID) -> None:
        if remote_id in self.peers or remote_id == self.node_id:
            return
        conn = await self.transport.dial(remote_id)
        # simultaneous dial+accept of the same peer: the check above ran
        # before the await — if the inbound side won, keep it
        if remote_id in self.peers:
            await conn.close()
            return
        self._add_peer(remote_id, conn)

    async def _accept_loop(self) -> None:
        while not self._stopping:
            try:
                conn = await self.transport.accept()
            except (ConnectionError, asyncio.CancelledError):
                return
            remote_id = conn.remote_id
            if remote_id in self.peers:
                await conn.close()
                continue
            self._add_peer(remote_id, conn)

    def _add_peer(self, node_id: NodeID, conn) -> None:
        assert node_id not in self.peers, f"duplicate peer {node_id[:8]}"
        peer = _Peer(node_id, conn)
        loop = asyncio.get_running_loop()
        peer.tasks.append(loop.create_task(self._peer_recv(peer)))
        peer.tasks.append(loop.create_task(self._peer_send(peer)))
        if self.ping_interval > 0:
            peer.tasks.append(loop.create_task(self._peer_keepalive(peer)))
        self.peers[node_id] = peer
        self.peers_connected += 1
        self.logger.info("peer up", peer=node_id[:8])
        self._publish_peer_update(PeerUpdate(node_id, PeerStatus.UP))

    async def disconnect(self, node_id: NodeID) -> None:
        """Drop a peer deliberately (seed-mode hangup, operator action)."""
        await self._disconnect(node_id)

    async def _disconnect(self, node_id: NodeID, notify: bool = True) -> None:
        peer = self.peers.pop(node_id, None)
        if peer is None:
            return
        self.peers_disconnected += 1
        await peer.conn.close()
        for t in peer.tasks:
            t.cancel()
        self.logger.info("peer down", peer=node_id[:8])
        if notify:
            self._publish_peer_update(PeerUpdate(node_id, PeerStatus.DOWN))

    # -- per-peer tasks ----------------------------------------------------
    def _count_recv(self, node_id: NodeID, channel_id: int, n: int) -> None:
        self.bytes_received[channel_id] = (
            self.bytes_received.get(channel_id, 0) + n
        )
        per = self.peer_bytes_received.get(node_id)
        if per is None:
            per = self.peer_bytes_received[node_id] = {}
        per[channel_id] = per.get(channel_id, 0) + n

    def _count_sent(self, node_id: NodeID, channel_id: int, n: int) -> None:
        self.bytes_sent[channel_id] = self.bytes_sent.get(channel_id, 0) + n
        per = self.peer_bytes_sent.get(node_id)
        if per is None:
            per = self.peer_bytes_sent[node_id] = {}
        per[channel_id] = per.get(channel_id, 0) + n

    async def _peer_recv(self, peer: _Peer) -> None:
        try:
            while True:
                channel_id, data = await peer.conn.receive()
                peer.last_recv = _clock.monotonic()
                self._count_recv(peer.node_id, channel_id, len(data))
                if channel_id == CTRL_CHANNEL:
                    if data == _PING:
                        peer.pong_owed = True
                        peer.send_ready.set()
                    # _PONG needs no action beyond the last_recv update
                    continue
                ch = self.channels.get(channel_id)
                if ch is None:
                    continue  # unknown channel: drop silently
                if len(data) > ch.descriptor.max_msg_bytes:
                    raise ValueError(f"oversized message on channel {channel_id:#x}")
                try:
                    msg = ch.descriptor.decode(data)
                except Exception as e:
                    raise ValueError(f"undecodable message: {e}")
                tname = type(msg).__name__
                self.msg_recv_count[tname] = self.msg_recv_count.get(tname, 0) + 1
                await ch.in_queue.put(
                    Envelope(message=msg, from_=peer.node_id, channel_id=channel_id)
                )
        except asyncio.CancelledError:
            return
        except (ConnectionError, Exception) as e:
            if not self._stopping and peer.node_id in self.peers:
                self.logger.info("peer recv ended", peer=peer.node_id[:8], err=str(e))
                asyncio.get_running_loop().create_task(self._disconnect(peer.node_id))

    def _pick_channel(self, peer: _Peer) -> int | None:
        """Non-empty channel with the lowest recently-sent/priority ratio
        (reference MConnection channel selection, connection.go:422-434):
        priority-weighted fair shares, no channel ever starved."""
        # fast path: exactly one channel has queued data — fairness math
        # is moot, and this is the common shape of a gossip burst (the
        # per-frame decay walk showed up on 100-node simnet profiles)
        busy = None
        for cid, q in peer.send_queues.items():
            if q:
                if busy is not None:   # second busy channel: need fairness
                    busy = None
                    break
                busy = cid
        else:
            return busy   # zero or one busy channel — no contest
        now = _clock.monotonic()
        # decay recentlySent ~0.8x per 100 ms (reference flush cadence)
        decay = 0.8 ** ((now - peer._recent_stamp) / 0.1)
        peer._recent_stamp = now
        best, best_ratio = None, None
        for cid, q in peer.send_queues.items():
            peer.recent_sent[cid] = peer.recent_sent.get(cid, 0.0) * decay
            if not q:
                continue
            prio = self.channels[cid].descriptor.priority if cid in self.channels else 1
            ratio = peer.recent_sent[cid] / max(prio, 1)
            if best_ratio is None or ratio < best_ratio:
                best, best_ratio = cid, ratio
        return best

    async def _peer_send(self, peer: _Peer) -> None:
        try:
            while True:
                await peer.send_ready.wait()
                peer.send_ready.clear()
                while True:
                    # control frames preempt everything: a pong delayed
                    # past pong_timeout by queued bulk data would read as
                    # death to the remote side
                    if peer.pong_owed:
                        peer.pong_owed = False
                        await peer.conn.send(CTRL_CHANNEL, _PONG)
                        self._count_sent(peer.node_id, CTRL_CHANNEL, len(_PONG))
                        continue
                    if peer.ping_due:
                        peer.ping_due = False
                        await peer.conn.send(CTRL_CHANNEL, _PING)
                        self._count_sent(peer.node_id, CTRL_CHANNEL, len(_PING))
                        continue
                    cid = self._pick_channel(peer)
                    if cid is None:
                        break
                    data = peer.send_queues[cid].popleft()
                    await peer.conn.send(cid, data)
                    peer.recent_sent[cid] = peer.recent_sent.get(cid, 0.0) + len(data)
                    self._count_sent(peer.node_id, cid, len(data))
        except asyncio.CancelledError:
            return
        except ConnectionError:
            if not self._stopping and peer.node_id in self.peers:
                asyncio.get_running_loop().create_task(self._disconnect(peer.node_id))

    async def _peer_keepalive(self, peer: _Peer) -> None:
        """Ping every ping_interval; if the peer sends NOTHING (pong or
        otherwise) for pong_timeout after a ping, evict it (reference
        connection.go:47-48,170-180).  A silently-dead TCP peer (NAT
        drop, SIGSTOP, power loss) is detected within
        ping_interval + pong_timeout instead of occupying a peer slot
        until the OS gives up (VERDICT r3 missing #2)."""
        try:
            next_ping = _clock.monotonic() + self.ping_interval
            while True:
                # pings hold the ping_interval cadence: the pong wait
                # overlaps the time until the next ping rather than
                # stretching the period to interval + timeout
                await asyncio.sleep(max(0.0, next_ping - _clock.monotonic()))
                t_ping = _clock.monotonic()
                next_ping = t_ping + self.ping_interval
                peer.ping_due = True
                peer.send_ready.set()
                await asyncio.sleep(self.pong_timeout)
                if peer.last_recv < t_ping:
                    self.logger.info(
                        "peer unresponsive, evicting",
                        peer=peer.node_id[:8],
                        silent_s=round(_clock.monotonic() - peer.last_recv, 1),
                    )
                    asyncio.get_running_loop().create_task(
                        self._disconnect(peer.node_id)
                    )
                    return
        except asyncio.CancelledError:
            return

    # -- channel routing ----------------------------------------------------
    async def _route_channel(self, ch: Channel) -> None:
        """Drain a channel's out-queue into per-peer per-channel queues."""
        cid = ch.channel_id
        cap = ch.descriptor.send_queue_capacity
        while True:
            try:
                env = await ch.out_queue.get()
            except asyncio.CancelledError:
                return
            data = ch.descriptor.encode(env.message)
            if env.broadcast:
                targets = [p for pid, p in self.peers.items() if pid != env.from_]
            else:
                p = self.peers.get(env.to)
                targets = [p] if p is not None else []
            for p in targets:
                q = p.send_queues.get(cid)
                if q is None:
                    q = p.send_queues[cid] = deque()
                if len(q) >= cap:
                    # backpressure: drop THIS channel's overflow only —
                    # other channels' queues are untouched (reference
                    # TrySend semantics + per-channel SendQueueCapacity)
                    self.logger.debug(
                        "channel send queue full", peer=p.node_id[:8], ch=cid
                    )
                    continue
                q.append(data)
                p.send_ready.set()

    async def _route_errors(self, ch: Channel) -> None:
        while True:
            try:
                perr = await ch.err_queue.get()
            except asyncio.CancelledError:
                return
            self.logger.info("peer error", peer=perr.node_id[:8], err=perr.err)
            await self._disconnect(perr.node_id)
