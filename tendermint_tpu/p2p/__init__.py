from .types import ChannelDescriptor, Envelope, NodeID, PeerStatus, PeerUpdate
from .channel import Channel
from .memory import MemoryNetwork, MemoryTransport
from .router import Router

__all__ = [
    "ChannelDescriptor",
    "Envelope",
    "NodeID",
    "PeerStatus",
    "PeerUpdate",
    "Channel",
    "MemoryNetwork",
    "MemoryTransport",
    "Router",
]
