"""P2P data model: node identity, envelopes, channel descriptors.

Parity: reference p2p/channel.go:10-58 (Envelope), p2p/transport.go:19
(ChannelDescriptor via conn.ChannelDescriptor), p2p/peer.go NodeID =
hex-encoded address of the node's ed25519 pubkey (p2p/key.go).

Design note (SURVEY §5.8): this is the new-style Channel/Router stack —
the reference's legacy Switch/Reactor model and its ReactorShim bridge
are skipped entirely; reactors here speak typed Envelopes natively.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Callable

from tendermint_tpu.crypto.keys import PubKey


def node_id_from_pubkey(pub: PubKey) -> str:
    """NodeID = lowercase hex of the 20-byte pubkey address."""
    return pub.address().hex()


NodeID = str  # lowercase hex address string


@dataclass
class Envelope:
    """One routed message (reference p2p/channel.go Envelope)."""

    message: object
    from_: NodeID = ""
    to: NodeID = ""
    broadcast: bool = False
    channel_id: int = 0


@dataclass
class ChannelDescriptor:
    """Static channel config registered by a reactor (reference
    conn.ChannelDescriptor + message codec)."""

    channel_id: int
    priority: int = 1
    encode: Callable[[object], bytes] = None
    decode: Callable[[bytes], object] = None
    recv_buffer_capacity: int = 1024
    max_msg_bytes: int = 1024 * 1024
    # per-peer bound on THIS channel's outbound queue (reference
    # conn.ChannelDescriptor.SendQueueCapacity): overflow drops this
    # channel's gossip only, never another channel's
    send_queue_capacity: int = 256


class PeerStatus(enum.Enum):
    UP = "up"
    DOWN = "down"


@dataclass
class PeerUpdate:
    node_id: NodeID
    status: PeerStatus
