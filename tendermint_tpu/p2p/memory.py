"""In-memory transport: a process-local network of queue-backed
connections keyed by NodeID.

Parity: reference p2p/transport_memory.go:23-394 — the fake backend for
multi-node tests without sockets.  Frames are (channel_id, bytes) pairs;
each direction is a bounded asyncio.Queue.
"""

from __future__ import annotations

import asyncio

from .types import NodeID


class MemoryConnection:
    """One side of a bidirectional in-memory connection."""

    def __init__(self, local_id: NodeID, remote_id: NodeID, send_q, recv_q):
        self.local_id = local_id
        self.remote_id = remote_id
        self._send_q: asyncio.Queue = send_q
        self._recv_q: asyncio.Queue = recv_q
        self._closed = asyncio.Event()
        # the other endpoint of the pair (linked by dial); close() signals
        # its _closed event directly so a close is NEVER lost to a full
        # queue — the EOF marker below is only the graceful-drain path
        self._peer: "MemoryConnection | None" = None

    async def send(self, channel_id: int, data: bytes) -> None:
        if self._closed.is_set():
            raise ConnectionError("connection closed")
        await self._send_q.put((channel_id, data))

    async def receive(self) -> tuple[int, bytes]:
        """Returns (channel_id, payload); raises ConnectionError on close."""
        if not self._recv_q.empty():
            # fast path: a frame is already queued — skip the two-future
            # wait below, which built and tore down two tasks per frame
            # and dominated the per-frame cost on busy simnet nets
            item = self._recv_q.get_nowait()
            if item is None:
                self._closed.set()
                raise ConnectionError("connection closed by peer")
            return item
        if self._closed.is_set():
            raise ConnectionError("connection closed")
        recv = asyncio.ensure_future(self._recv_q.get())
        closed = asyncio.ensure_future(self._closed.wait())
        done, _ = await asyncio.wait({recv, closed}, return_when=asyncio.FIRST_COMPLETED)
        if recv in done:
            closed.cancel()
            item = recv.result()
            if item is None:
                self._closed.set()
                raise ConnectionError("connection closed by peer")
            return item
        recv.cancel()
        raise ConnectionError("connection closed")

    async def close(self) -> None:
        if not self._closed.is_set():
            self._closed.set()
            try:
                self._send_q.put_nowait(None)  # EOF marker for the peer
            except asyncio.QueueFull:
                # marker lost — the remote's _closed event (below) still
                # delivers the close.  Dropping it silently used to leave
                # a slow peer (full queue = exactly the slow-peer case)
                # blocked in receive() forever.
                pass
            peer = self._peer
            if peer is not None:
                peer._closed.set()

    @property
    def closed(self) -> bool:
        return self._closed.is_set()


class MemoryTransport:
    """Per-node endpoint in a MemoryNetwork."""

    # subclass hooks (simnet FaultyTransport swaps the connection type)
    connection_class = MemoryConnection
    queue_maxsize = 1024

    def __init__(self, network: "MemoryNetwork", node_id: NodeID):
        self.network = network
        self.node_id = node_id
        self._accept_q: asyncio.Queue[MemoryConnection] = asyncio.Queue()
        self._closed = False
        # every connection this endpoint ever handed out (either side of
        # a dial), so a whole-node teardown can sever them all
        self.conns: list[MemoryConnection] = []

    async def accept(self) -> MemoryConnection:
        conn = await self._accept_q.get()
        if conn is None:
            raise ConnectionError("transport closed")
        self.conns.append(conn)
        return conn

    async def dial(self, remote_id: NodeID) -> MemoryConnection:
        remote = self.network.nodes.get(remote_id)
        if remote is None or remote._closed:
            raise ConnectionError(f"no node {remote_id} in memory network")
        cls = self.connection_class
        q_ab: asyncio.Queue = asyncio.Queue(maxsize=self.queue_maxsize)
        q_ba: asyncio.Queue = asyncio.Queue(maxsize=self.queue_maxsize)
        local_conn = cls(self.node_id, remote_id, q_ab, q_ba)
        remote_conn = cls(remote_id, self.node_id, q_ba, q_ab)
        # link the pair: close() on either side must reach the other even
        # when its queue is full (the EOF marker alone can be dropped)
        local_conn._peer = remote_conn
        remote_conn._peer = local_conn
        self._setup_conn(local_conn)
        remote._setup_conn(remote_conn)
        self.conns.append(local_conn)
        await remote._accept_q.put(remote_conn)
        return local_conn

    def _setup_conn(self, conn: MemoryConnection) -> None:
        """Subclass hook: initialize a freshly-created connection side
        (the fault layer attaches its network handle here)."""

    async def close(self) -> None:
        self._closed = True
        self.network.nodes.pop(self.node_id, None)
        await self._accept_q.put(None)


class MemoryNetwork:
    """Registry of in-process transports (reference MemoryNetwork)."""

    def __init__(self):
        self.nodes: dict[NodeID, MemoryTransport] = {}

    def create_transport(self, node_id: NodeID) -> MemoryTransport:
        if node_id in self.nodes:
            raise ValueError(f"node {node_id} already in network")
        t = MemoryTransport(self, node_id)
        self.nodes[node_id] = t
        return t
