"""Typed bidirectional channel between a reactor and the router.

Parity: reference p2p/channel.go:10-130 — a reactor sends Envelopes out
(unicast or broadcast) and receives inbound Envelopes; errors on a peer
are reported through `error()` which makes the router drop the peer
(reference PeerError / StopPeerForError semantics).
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass

from .types import ChannelDescriptor, Envelope, NodeID


@dataclass
class PeerError:
    node_id: NodeID
    err: str


class Channel:
    def __init__(self, descriptor: ChannelDescriptor):
        self.descriptor = descriptor
        self.in_queue: asyncio.Queue[Envelope] = asyncio.Queue(
            maxsize=descriptor.recv_buffer_capacity
        )
        self.out_queue: asyncio.Queue[Envelope] = asyncio.Queue(maxsize=1024)
        self.err_queue: asyncio.Queue[PeerError] = asyncio.Queue(maxsize=256)
        # per-message-type send counters + out-queue drop count (reference
        # p2p/metrics.go MessageSendBytesTotal{message_type}); scraped by
        # node/metrics.py, aggregated across channels
        self.msg_send_count: dict[str, int] = {}
        self.send_drops = 0

    @property
    def channel_id(self) -> int:
        return self.descriptor.channel_id

    def _count_send(self, envelope: Envelope) -> None:
        name = type(envelope.message).__name__
        self.msg_send_count[name] = self.msg_send_count.get(name, 0) + 1

    async def send(self, envelope: Envelope) -> None:
        envelope.channel_id = self.channel_id
        self._count_send(envelope)
        await self.out_queue.put(envelope)

    def try_send(self, envelope: Envelope) -> bool:
        """Non-blocking send; drops on a full queue (reference TrySend)."""
        envelope.channel_id = self.channel_id
        try:
            self.out_queue.put_nowait(envelope)
            self._count_send(envelope)
            return True
        except asyncio.QueueFull:
            self.send_drops += 1
            return False

    async def receive(self) -> Envelope:
        return await self.in_queue.get()

    async def error(self, node_id: NodeID, err: str) -> None:
        await self.err_queue.put(PeerError(node_id, err))
