"""Authenticated encrypted connection: STS handshake + AEAD framing.

Parity: reference p2p/conn/secret_connection.go:92-465 — ephemeral
X25519 ECDH, key schedule, then each side proves its node identity by
signing the session challenge with its ed25519 node key.  The remote
NodeID (hex address of the authenticated pubkey) is only trusted after
that signature verifies.

Deviations from the reference, deliberate (SURVEY §5.8 allows a
re-keyed wire format as long as the *semantics* — mutual authentication,
confidentiality, per-direction nonce discipline — match):
- HKDF-SHA256 keyed on the ECDH secret with the sorted ephemeral pubkeys
  as transcript salt replaces the merlin transcript construction.
- Messages are sealed whole (4-byte length + ciphertext) instead of the
  reference's fixed 1024-byte frames; padding for traffic analysis is a
  non-goal here.
- Low-order-point rejection (secret_connection.go:44) is inherited from
  the X25519 implementation, which rejects all-zero shared secrets.
"""

from __future__ import annotations

import asyncio
import hashlib
import struct

try:
    # Gated, not required at import: without the `cryptography` package
    # an encrypted connection is impossible, but eagerly importing it
    # here used to take the whole p2p/node package down with it on a
    # minimal container.  Handshake/seal paths raise at the point of
    # use instead.
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
    from cryptography.hazmat.primitives.hashes import SHA256
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF

    _HAVE_AEAD = True
except Exception:  # pragma: no cover — ModuleNotFoundError and kin
    _HAVE_AEAD = False

from tendermint_tpu.crypto.keys import PrivKey, PubKey

_KDF_INFO = b"TENDERMINT_TPU_SECRET_CONNECTION_KEY_AND_CHALLENGE_GEN"
# Cap on one sealed message: must clear the largest registered channel
# message (blocksync BlockResponse ≈ 22 MiB, statesync chunks 16 MiB) —
# the per-channel max_msg_bytes check in the Router is the real bound.
_MAX_CT_LEN = 32 * 1024 * 1024
_AUTH_MSG_FMT = "32s64s"  # pubkey bytes + ed25519 signature


class HandshakeError(ConnectionError):
    pass


class _NonceSeq:
    """96-bit little-endian counter nonce, one per direction
    (reference nonceLE/incrNonce)."""

    def __init__(self) -> None:
        self._n = 0

    def next(self) -> bytes:
        n = self._n
        self._n += 1
        if n >= 1 << 96:
            raise ConnectionError("nonce space exhausted")
        return n.to_bytes(12, "little")


class SecretConnection:
    """Encrypted, mutually-authenticated stream. Construct via
    `await SecretConnection.handshake(reader, writer, priv_key)`."""

    def __init__(self, reader, writer, send_key: bytes, recv_key: bytes,
                 remote_pub: PubKey):
        self._reader = reader
        self._writer = writer
        self._send = ChaCha20Poly1305(send_key)
        self._recv = ChaCha20Poly1305(recv_key)
        self._send_nonce = _NonceSeq()
        self._recv_nonce = _NonceSeq()
        self.remote_pub = remote_pub

    # -- handshake -------------------------------------------------------
    @classmethod
    async def handshake(cls, reader, writer, priv_key: PrivKey,
                        timeout: float = 10.0) -> "SecretConnection":
        return await asyncio.wait_for(
            cls._handshake(reader, writer, priv_key), timeout
        )

    @classmethod
    async def _handshake(cls, reader, writer, priv_key: PrivKey) -> "SecretConnection":
        if not _HAVE_AEAD:
            raise HandshakeError(
                "secret connections require the 'cryptography' package, "
                "which is not installed in this environment"
            )
        # 1. exchange ephemeral X25519 pubkeys in the clear
        eph_priv = X25519PrivateKey.generate()
        eph_pub = eph_priv.public_key().public_bytes_raw()
        writer.write(eph_pub)
        await writer.drain()
        remote_eph = await reader.readexactly(32)

        # 2. ECDH → key schedule.  Sorting the two ephemeral keys gives
        # both sides the same transcript; the side holding the LOWER key
        # uses (key1=send, key2=recv), the higher the reverse
        # (reference secret_connection.go deriveSecretsAndChallenge).
        try:
            shared = eph_priv.exchange(X25519PublicKey.from_public_bytes(remote_eph))
        except ValueError as e:  # all-zero secret: low-order remote point
            raise HandshakeError(f"bad ephemeral key: {e}") from None
        lo, hi = sorted((eph_pub, remote_eph))
        okm = HKDF(
            algorithm=SHA256(), length=96, salt=hashlib.sha256(lo + hi).digest(),
            info=_KDF_INFO,
        ).derive(shared)
        key1, key2, challenge = okm[:32], okm[32:64], okm[64:]
        if eph_pub == lo:
            send_key, recv_key = key1, key2
        else:
            send_key, recv_key = key2, key1
        conn = cls(reader, writer, send_key, recv_key, remote_pub=None)

        # 3. authenticate: sign the shared challenge with the node key,
        # exchange (pubkey, sig) over the now-encrypted channel
        sig = priv_key.sign(challenge)
        await conn.send(struct.pack(_AUTH_MSG_FMT, priv_key.pub_key().bytes_(), sig))
        auth = await conn.receive()
        if len(auth) != struct.calcsize(_AUTH_MSG_FMT):
            raise HandshakeError("malformed auth message")
        remote_pub_bytes, remote_sig = struct.unpack(_AUTH_MSG_FMT, auth)
        remote_pub = PubKey(remote_pub_bytes)
        if not remote_pub.verify_signature(challenge, remote_sig):
            raise HandshakeError("challenge signature verification failed")
        conn.remote_pub = remote_pub
        return conn

    # -- sealed message I/O ----------------------------------------------
    async def send(self, plaintext: bytes) -> None:
        ct = self._send.encrypt(self._send_nonce.next(), plaintext, None)
        self._writer.write(struct.pack(">I", len(ct)) + ct)
        await self._writer.drain()

    async def receive(self) -> bytes:
        head = await self._reader.readexactly(4)
        (n,) = struct.unpack(">I", head)
        if n == 0 or n > _MAX_CT_LEN:
            raise ConnectionError(f"bad sealed frame length {n}")
        ct = await self._reader.readexactly(n)
        try:
            return self._recv.decrypt(self._recv_nonce.next(), ct, None)
        except Exception as e:
            raise ConnectionError(f"AEAD open failed: {e}") from None
