"""Peer exchange (PEX): address book + discovery reactor.

Parity: reference p2p/pex/addrbook.go:119 (bucketed new/old address
book, good/bad marking, atomic JSON persistence) and
p2p/pex/pex_reactor.go:133 (channel 0x00 addr request/response with
per-peer rate limiting, seed mode crawl-and-disconnect, ensure-peers
dialing loop toward max_num_outbound_peers).

Simplifications vs the reference, deliberate: buckets are hashed by
address group like the reference but without the 64/256 bucket split
constants (a dict of group → entries with the same old/new promotion
semantics); the trust-metric store (p2p/trust, loosely integrated there)
is folded into per-address attempt/success counters here.
"""

from __future__ import annotations

import asyncio
import json
import os
import random
import time
from dataclasses import dataclass, field

from tendermint_tpu.utils.log import Logger, nop_logger

from .tcp import parse_net_address
from .types import ChannelDescriptor, Envelope, NodeID, PeerStatus

PEX_CHANNEL = 0x00

# reference pex_reactor.go: one request per peer per interval
_REQUEST_INTERVAL_S = 30.0
_MAX_ADDRS_PER_MSG = 100
_ENSURE_PEERS_INTERVAL_S = 2.0


@dataclass
class KnownAddress:
    """reference p2p/pex/known_address.go"""

    node_id: NodeID
    host: str
    port: int
    attempts: int = 0
    last_attempt: float = 0.0
    last_success: float = 0.0
    bucket: str = "new"  # "new" | "old"

    @property
    def addr(self) -> str:
        return f"{self.node_id}@{self.host}:{self.port}"

    def is_bad(self) -> bool:
        # reference known_address.go isBad: too many failed attempts
        return self.attempts >= 3 and self.last_success == 0


class AddrBook:
    """reference p2p/pex/addrbook.go — new/old promotion, persistence."""

    def __init__(self, file_path: str = "", strict: bool = True,
                 logger: Logger | None = None):
        self.file_path = file_path
        self.strict = strict
        self.logger = logger or nop_logger()
        self.addrs: dict[NodeID, KnownAddress] = {}
        self._our_ids: set[NodeID] = set()
        if file_path and os.path.exists(file_path):
            self.load()

    def add_our_id(self, node_id: NodeID) -> None:
        self._our_ids.add(node_id)
        self.addrs.pop(node_id, None)

    def add_address(self, addr: str) -> bool:
        """Returns True if new/updated (reference AddAddress)."""
        try:
            node_id, host, port = parse_net_address(addr)
        except ValueError:
            return False
        if node_id in self._our_ids:
            return False
        if self.strict and not _routable(host):
            return False
        known = self.addrs.get(node_id)
        if known is None:
            self.addrs[node_id] = KnownAddress(node_id, host, port)
            return True
        if known.bucket == "new" and (known.host, known.port) != (host, port):
            # new-bucket addresses may be refreshed; old (proven) stick
            known.host, known.port = host, port
            return True
        return False

    def mark_attempt(self, node_id: NodeID) -> None:
        ka = self.addrs.get(node_id)
        if ka:
            ka.attempts += 1
            ka.last_attempt = time.time()

    def mark_good(self, node_id: NodeID) -> None:
        """Connected + useful → promote to old (reference MarkGood)."""
        ka = self.addrs.get(node_id)
        if ka:
            ka.attempts = 0
            ka.last_success = time.time()
            ka.bucket = "old"

    def mark_bad(self, node_id: NodeID) -> None:
        self.addrs.pop(node_id, None)

    def pick_address(self, exclude: set[NodeID]) -> KnownAddress | None:
        """Biased pick: prefer old (proven) addresses ~2/3 of the time
        (reference PickAddress bias)."""
        cands = [a for a in self.addrs.values()
                 if a.node_id not in exclude and not a.is_bad()]
        if not cands:
            return None
        old = [a for a in cands if a.bucket == "old"]
        new = [a for a in cands if a.bucket == "new"]
        pool = old if (old and (not new or random.random() < 0.65)) else new
        return random.choice(pool)

    def sample(self, n: int = _MAX_ADDRS_PER_MSG) -> list[str]:
        """Random subset for PEX responses (reference GetSelection)."""
        pool = [a.addr for a in self.addrs.values() if not a.is_bad()]
        random.shuffle(pool)
        return pool[:n]

    def size(self) -> int:
        return len(self.addrs)

    # -- persistence (atomic JSON, reference pex/file.go) ---------------
    def save(self) -> None:
        if not self.file_path:
            return
        doc = {
            "addrs": [
                {"id": a.node_id, "host": a.host, "port": a.port,
                 "bucket": a.bucket, "attempts": a.attempts,
                 "last_success": a.last_success}
                for a in self.addrs.values()
            ]
        }
        tmp = self.file_path + ".tmp"
        os.makedirs(os.path.dirname(self.file_path) or ".", exist_ok=True)
        with open(tmp, "w") as fh:
            json.dump(doc, fh)
        os.replace(tmp, self.file_path)

    def load(self) -> None:
        try:
            with open(self.file_path) as fh:
                doc = json.load(fh)
            for e in doc.get("addrs", []):
                ka = KnownAddress(e["id"], e["host"], int(e["port"]),
                                  bucket=e.get("bucket", "new"),
                                  attempts=int(e.get("attempts", 0)),
                                  last_success=float(e.get("last_success", 0)))
                self.addrs[ka.node_id] = ka
        except (OSError, json.JSONDecodeError, KeyError, ValueError) as e:
            self.logger.error("addrbook load failed", err=str(e))


def _routable(host: str) -> bool:
    """reference netaddress.go Routable — loopback/private ranges are
    unroutable under strict mode."""
    if host in ("localhost",):
        return False
    parts = host.split(".")
    if len(parts) == 4 and all(p.isdigit() for p in parts):
        a, b = int(parts[0]), int(parts[1])
        if a == 127 or a == 10 or a == 0:
            return False
        if a == 192 and b == 168:
            return False
        if a == 172 and 16 <= b <= 31:
            return False
        if a == 169 and b == 254:
            return False
    if host == "::1":
        return False
    return True


# ---------------------------------------------------------------------------
# wire messages (channel 0x00)
# ---------------------------------------------------------------------------

@dataclass
class PexRequest:
    pass


@dataclass
class PexResponse:
    addrs: list[str] = field(default_factory=list)


def _encode(msg) -> bytes:
    if isinstance(msg, PexRequest):
        return b"\x01"
    return b"\x02" + json.dumps(msg.addrs).encode()


def _decode(data: bytes):
    if not data:
        raise ValueError("empty pex message")
    if data[0] == 1:
        return PexRequest()
    if data[0] == 2:
        addrs = json.loads(data[1:])
        if not isinstance(addrs, list) or len(addrs) > _MAX_ADDRS_PER_MSG:
            raise ValueError("bad pex response")
        return PexResponse([str(a) for a in addrs])
    raise ValueError(f"unknown pex message {data[0]}")


class PexReactor:
    """Discovery + outbound-connection maintenance
    (reference p2p/pex/pex_reactor.go)."""

    def __init__(self, router, book: AddrBook, transport,
                 max_outbound: int = 10, seed_mode: bool = False,
                 private_ids: set[NodeID] | None = None,
                 logger: Logger | None = None):
        self.router = router
        self.book = book
        self.transport = transport  # TCPTransport (address registration)
        self.max_outbound = max_outbound
        self.seed_mode = seed_mode
        # never gossiped to other peers (reference sw.AddPrivatePeerIDs /
        # config.p2p.private_peer_ids)
        self.private_ids: set[NodeID] = set(private_ids or ())
        self.logger = logger or nop_logger()
        self.ch = router.open_channel(ChannelDescriptor(
            channel_id=PEX_CHANNEL, priority=1,
            encode=_encode, decode=_decode,
            max_msg_bytes=64 * 1024,
        ))
        self.peer_updates = router.subscribe_peer_updates()
        self._last_request: dict[NodeID, float] = {}
        self._flood_strikes: dict[NodeID, int] = {}
        self._requested: set[NodeID] = set()
        self._tasks: list[asyncio.Task] = []
        self.book.add_our_id(router.node_id)

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        for fn in (self._recv_loop, self._peer_update_loop, self._ensure_peers_loop):
            self._tasks.append(loop.create_task(fn()))

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self.book.save()

    # -- receive ---------------------------------------------------------
    async def _recv_loop(self) -> None:
        while True:
            env = await self.ch.receive()
            msg = env.message
            if isinstance(msg, PexRequest):
                now = time.monotonic()
                last = self._last_request.get(env.from_, 0.0)
                if now - last < _REQUEST_INTERVAL_S * 0.9:
                    # Too-soon request: ignore it, and only treat a PATTERN
                    # of early requests as abuse.  (A reconnecting peer's
                    # first request can race the peer-update that resets
                    # its session state — one early request is normal.)
                    strikes = self._flood_strikes.get(env.from_, 0) + 1
                    self._flood_strikes[env.from_] = strikes
                    if strikes >= 3:
                        await self.ch.error(env.from_, "pex request flood")
                    continue
                self._flood_strikes.pop(env.from_, None)
                self._last_request[env.from_] = now
                addrs = [a for a in self.book.sample()
                         if a.split("@", 1)[0] not in self.private_ids]
                await self.ch.send(Envelope(
                    to=env.from_, message=PexResponse(addrs)
                ))
                if self.seed_mode:
                    # seed: serve addresses then hang up to stay available
                    # (reference SeedDisconnectWaitPeriod behavior)
                    await asyncio.sleep(1.0)
                    await self.router.disconnect(env.from_)
            elif isinstance(msg, PexResponse):
                if env.from_ not in self._requested:
                    # unsolicited: drop without learning addresses (the
                    # pollution defense) — no disconnect, a reconnect race
                    # can legitimately produce one stray response
                    self.logger.debug("unsolicited pex response ignored",
                                      peer=env.from_[:8])
                    continue
                self._requested.discard(env.from_)
                added = sum(1 for a in msg.addrs if self.book.add_address(a))
                if added:
                    self.logger.debug("pex learned addresses", n=added)
                    self.book.save()

    async def _peer_update_loop(self) -> None:
        while True:
            update = await self.peer_updates.get()
            if update.status == PeerStatus.UP:
                # per-connection state: a reconnecting peer starts fresh —
                # the flood limiter must only see requests from ONE session
                self._last_request.pop(update.node_id, None)
                # ask a fresh peer for its addresses once
                self._requested.add(update.node_id)
                await self.ch.send(Envelope(to=update.node_id, message=PexRequest()))
            else:
                self._last_request.pop(update.node_id, None)
                self._flood_strikes.pop(update.node_id, None)
                self._requested.discard(update.node_id)

    # -- dialing ---------------------------------------------------------
    async def _ensure_peers_loop(self) -> None:
        """Keep dialing discovered addresses until we hold max_outbound
        connections (reference ensurePeersRoutine)."""
        while True:
            await asyncio.sleep(_ENSURE_PEERS_INTERVAL_S)
            need = self.max_outbound - len(self.router.peers)
            if need <= 0:
                continue
            exclude = set(self.router.peers) | {self.router.node_id}
            for _ in range(min(need, 3)):  # a few dials per tick
                ka = self.book.pick_address(exclude)
                if ka is None:
                    break
                exclude.add(ka.node_id)
                self.book.mark_attempt(ka.node_id)
                try:
                    if hasattr(self.transport, "add_peer_address"):
                        self.transport.add_peer_address(ka.addr)
                    await self.router.dial(ka.node_id)
                    self.book.mark_good(ka.node_id)
                except (ConnectionError, OSError, asyncio.TimeoutError) as e:
                    self.logger.debug("pex dial failed", peer=ka.node_id[:8],
                                      err=str(e))
                    if self.book.addrs.get(ka.node_id, KnownAddress("", "", 0)).is_bad():
                        self.book.mark_bad(ka.node_id)
