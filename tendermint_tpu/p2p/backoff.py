"""Redial backoff policy: capped exponential with seeded jitter and
flap detection.

The persistent-peer dialer (node.py) and the simnet mesh keeper both
need the same policy: retry a dead peer with exponentially growing,
jittered, CAPPED delays — and do NOT treat a momentary success as
recovery.  The pre-existing dialer reset its backoff to the floor the
instant a dial succeeded, so a flapping peer (accepts the connection,
dies within a second, forever) was redialed at the floor rate
indefinitely: a busy-loop with extra steps.  `DialBackoff` only resets
after the connection SURVIVES `min_uptime_s`.

Jitter is drawn from a seeded `random.Random` (TM_TPU_DIAL_SEED pins it
for tests; the default decorrelates processes AND instances within one
process, same scheme as the reactor's maj23 jitter) so a fleet of nodes
restarting against one dead peer doesn't thundering-herd it in
lock-step — and so a simnet run replays identically for a given seed.

Pure logic over caller-supplied clocks: no sleeping, no wall-clock
reads, trivially unit-testable.
"""

from __future__ import annotations

import os
import random


class DialBackoff:
    """Per-peer redial delay policy.

    Usage from a dial loop:
        delay = bo.next_delay(pid)        # after a failed dial attempt
        bo.note_connected(pid, now)       # dial succeeded
        bo.note_disconnected(pid, now)    # peer died; resets the ladder
                                          # only if uptime >= min_uptime_s
    """

    def __init__(self, base_s: float = 0.5, cap_s: float = 30.0,
                 min_uptime_s: float = 10.0, rng: random.Random | None = None):
        if rng is None:
            seed = os.environ.get("TM_TPU_DIAL_SEED")
            rng = random.Random(
                int(seed) if seed else hash((os.getpid(), id(self))))
        self.base_s = base_s
        self.cap_s = cap_s
        self.min_uptime_s = min_uptime_s
        self._rng = rng
        self._attempts: dict[str, int] = {}
        self._connected_at: dict[str, float] = {}

    def next_delay(self, peer_id: str) -> float:
        """Delay before the next dial attempt; advances the ladder."""
        n = self._attempts.get(peer_id, 0)
        self._attempts[peer_id] = n + 1
        raw = min(self.cap_s, self.base_s * (2.0 ** n))
        # jitter in [0.5x, 1.0x]: spreads simultaneous redialers without
        # ever shrinking the delay below half the deterministic ladder
        return raw * (0.5 + 0.5 * self._rng.random())

    def note_connected(self, peer_id: str, now: float) -> None:
        self._connected_at[peer_id] = now

    def note_disconnected(self, peer_id: str, now: float) -> None:
        """Reset the ladder only after a PROVEN-stable connection: a
        peer that dies within min_uptime_s keeps climbing, so a flapping
        peer converges to cap_s-spaced dials instead of busy-looping at
        the floor."""
        connected_at = self._connected_at.pop(peer_id, None)
        if connected_at is not None and now - connected_at >= self.min_uptime_s:
            self._attempts.pop(peer_id, None)

    def attempts(self, peer_id: str) -> int:
        return self._attempts.get(peer_id, 0)

    def forget(self, peer_id: str) -> None:
        self._attempts.pop(peer_id, None)
        self._connected_at.pop(peer_id, None)
