"""Redial backoff policy: capped exponential with seeded jitter and
flap detection.

The persistent-peer dialer (node.py) and the simnet mesh keeper both
need the same policy: retry a dead peer with exponentially growing,
jittered, CAPPED delays — and do NOT treat a momentary success as
recovery.  The pre-existing dialer reset its backoff to the floor the
instant a dial succeeded, so a flapping peer (accepts the connection,
dies within a second, forever) was redialed at the floor rate
indefinitely: a busy-loop with extra steps.  `DialBackoff` only resets
after the connection SURVIVES `min_uptime_s`.

Jitter is drawn from a seeded `random.Random` (TM_TPU_DIAL_SEED pins it
for tests; the default decorrelates processes AND instances within one
process, same scheme as the reactor's maj23 jitter) so a fleet of nodes
restarting against one dead peer doesn't thundering-herd it in
lock-step — and so a simnet run replays identically for a given seed.

Pure logic over caller-supplied clocks: no sleeping, no wall-clock
reads, trivially unit-testable.
"""

from __future__ import annotations

import os
import random


class DialBackoff:
    """Per-peer redial delay policy.

    Usage from a dial loop:
        delay = bo.next_delay(pid)        # after a failed dial attempt
        bo.note_connected(pid, now)       # dial succeeded
        bo.note_disconnected(pid, now)    # peer died; resets the ladder
                                          # only if uptime >= min_uptime_s
    """

    def __init__(self, base_s: float = 0.5, cap_s: float = 30.0,
                 min_uptime_s: float = 10.0, rng: random.Random | None = None):
        if rng is None:
            seed = os.environ.get("TM_TPU_DIAL_SEED")
            rng = random.Random(
                int(seed) if seed else hash((os.getpid(), id(self))))
        self.base_s = base_s
        self.cap_s = cap_s
        self.min_uptime_s = min_uptime_s
        self._rng = rng
        self._attempts: dict[str, int] = {}
        self._connected_at: dict[str, float] = {}
        # flap counter: connections that died before proving stable
        # (uptime < min_uptime_s).  The remediation layer's per-peer
        # score — a chronic flapper accumulates these while a peer that
        # eventually sticks gets wiped by the ladder reset.
        self._flaps: dict[str, int] = {}

    def next_delay(self, peer_id: str) -> float:
        """Delay before the next dial attempt; advances the ladder."""
        n = self._attempts.get(peer_id, 0)
        self._attempts[peer_id] = n + 1
        raw = min(self.cap_s, self.base_s * (2.0 ** n))
        # jitter in [0.5x, 1.0x]: spreads simultaneous redialers without
        # ever shrinking the delay below half the deterministic ladder
        return raw * (0.5 + 0.5 * self._rng.random())

    def note_connected(self, peer_id: str, now: float) -> None:
        self._connected_at[peer_id] = now

    def note_disconnected(self, peer_id: str, now: float) -> None:
        """Reset the ladder only after a PROVEN-stable connection: a
        peer that dies within min_uptime_s keeps climbing, so a flapping
        peer converges to cap_s-spaced dials instead of busy-looping at
        the floor.  An early death also counts a flap — the remediation
        layer's eviction score."""
        connected_at = self._connected_at.pop(peer_id, None)
        if connected_at is None:
            return
        if now - connected_at >= self.min_uptime_s:
            self._attempts.pop(peer_id, None)
            self._flaps.pop(peer_id, None)
        else:
            self._flaps[peer_id] = self._flaps.get(peer_id, 0) + 1

    def attempts(self, peer_id: str) -> int:
        return self._attempts.get(peer_id, 0)

    def flaps(self, peer_id: str) -> int:
        return self._flaps.get(peer_id, 0)

    def reset(self, peer_id: str) -> None:
        """Hard ladder reset: the peer's next dial starts from rung 0
        with a clean flap score.  The remediation layer calls this when
        a quarantined peer is pardoned — without it, a pardoned peer
        would inherit its stale (usually capped) rung and the clean
        reconnect it earned would still wait cap_s."""
        self._attempts.pop(peer_id, None)
        self._connected_at.pop(peer_id, None)
        self._flaps.pop(peer_id, None)

    def forget(self, peer_id: str) -> None:
        self.reset(peer_id)

    def peer_state(self, peer_id: str) -> dict:
        """One peer's ladder view for the scoring layer."""
        return {
            "attempts": self._attempts.get(peer_id, 0),
            "flaps": self._flaps.get(peer_id, 0),
            "connected": peer_id in self._connected_at,
        }

    def peer_states(self) -> dict[str, dict]:
        """Every peer the ladder has seen -> its state snapshot (the
        remediation controller's eviction-scoring input)."""
        peers = (set(self._attempts) | set(self._connected_at)
                 | set(self._flaps))
        return {pid: self.peer_state(pid) for pid in sorted(peers)}
