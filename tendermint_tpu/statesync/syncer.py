"""Syncer: drives a snapshot restore — discovery → offer → fetch →
apply → verify.

Parity: reference statesync/syncer.go (SyncAny :141, syncer.Sync :228,
offerSnapshot :294, fetchChunks :384 with 4 workers, applyChunks :330
with RETRY/RETRY_SNAPSHOT/REJECT_SNAPSHOT/refetch_chunks/reject_senders
verbs, verifyApp :448).

The reactor owns the wire; the syncer talks to it through two callables
(request_snapshots, request_chunk) and receives inbound snapshots/chunks
via add_snapshot/add_chunk.  This keeps the restore logic a pure async
state machine, testable without a network.
"""

from __future__ import annotations

import asyncio

from tendermint_tpu.abci.types import (
    ResponseApplySnapshotChunk,
    ResponseOfferSnapshot,
    Snapshot,
)
from tendermint_tpu.utils.log import Logger, nop_logger

from .chunks import ChunkQueue
from .snapshots import SnapshotPool

CHUNK_FETCHERS = 4  # syncer.go:38
CHUNK_REQUEST_TIMEOUT = 10.0  # syncer.go:41


class SyncAbortedError(Exception):
    """App returned ABORT — the node must halt."""


class _SnapshotRejectedError(Exception):
    """Current snapshot failed; try the next-best one."""


class Syncer:
    def __init__(
        self,
        app_snapshot_conn,
        state_provider,
        request_snapshots,
        request_chunk,
        logger: Logger | None = None,
        chunk_timeout: float = CHUNK_REQUEST_TIMEOUT,
    ):
        self.app = app_snapshot_conn
        self.state_provider = state_provider
        self.request_snapshots = request_snapshots  # async () -> None (broadcast)
        self.request_chunk = request_chunk  # async (peer_id, snapshot, index) -> None
        self.logger = logger or nop_logger()
        self.chunk_timeout = chunk_timeout
        self.pool = SnapshotPool()
        self._chunk_queue: ChunkQueue | None = None
        self._new_snapshot = asyncio.Event()

    # -- reactor intake --------------------------------------------------
    def add_snapshot(self, peer_id: str, snapshot: Snapshot) -> bool:
        added = self.pool.add(peer_id, snapshot)
        if added:
            self._new_snapshot.set()
        return added

    def add_chunk(self, peer_id: str, height: int, format: int, index: int, chunk: bytes) -> bool:
        q = self._chunk_queue
        if q is None or q.snapshot.height != height or q.snapshot.format != format:
            return False
        return q.add(index, chunk, peer_id)

    def remove_peer(self, peer_id: str) -> None:
        self.pool.remove_peer(peer_id)

    # -- main entry ------------------------------------------------------
    async def sync_any(self, discovery_time: float = 2.0, retries: int | None = None):
        """Try snapshots best-first until one restores; returns
        (state, commit) for node bootstrap (syncer.go SyncAny)."""
        await self.request_snapshots()
        await asyncio.sleep(discovery_time)
        attempts = 0
        while True:
            snapshot = self.pool.best()
            if snapshot is None:
                attempts += 1
                if retries is not None and attempts >= retries:
                    raise TimeoutError("no viable snapshots discovered")
                await self.request_snapshots()
                self._new_snapshot.clear()
                try:
                    await asyncio.wait_for(self._new_snapshot.wait(), discovery_time)
                except asyncio.TimeoutError:
                    pass
                continue
            try:
                return await self._sync_snapshot(snapshot)
            except _SnapshotRejectedError:
                continue  # pool already updated; try next-best

    async def _sync_snapshot(self, snapshot: Snapshot):
        """syncer.go Sync: one snapshot attempt end-to-end."""
        self.logger.info(
            "offering snapshot", height=snapshot.height, format=snapshot.format
        )
        # trusted app hash BEFORE offering (syncer.go:255-266): the header
        # at height+1 commits the app hash the restored state must match;
        # this also probes that height+2 exists (a snapshot at the chain
        # tip can't produce a State yet) — reject such snapshots and try
        # the next-best one
        try:
            app_hash = self.state_provider.app_hash(snapshot.height)
        except Exception as e:
            self.logger.info(
                "snapshot unusable (no verifiable app hash)",
                height=snapshot.height,
                err=str(e),
            )
            self.pool.reject(snapshot)
            raise _SnapshotRejectedError from e

        resp = self.app.offer_snapshot_sync(snapshot, app_hash)
        r = ResponseOfferSnapshot.Result
        if resp.result == r.ACCEPT:
            pass
        elif resp.result == r.ABORT:
            raise SyncAbortedError("app aborted snapshot restore")
        elif resp.result == r.REJECT:
            self.pool.reject(snapshot)
            raise _SnapshotRejectedError
        elif resp.result == r.REJECT_FORMAT:
            self.pool.reject_format(snapshot.format)
            raise _SnapshotRejectedError
        elif resp.result == r.REJECT_SENDER:
            for p in self.pool.get_peers(snapshot):
                self.pool.reject_peer(p)
            raise _SnapshotRejectedError
        else:
            raise SyncAbortedError(f"unknown OfferSnapshot result {resp.result}")

        self._chunk_queue = ChunkQueue(snapshot)
        fetchers = [
            asyncio.get_running_loop().create_task(self._fetch_loop(snapshot))
            for _ in range(CHUNK_FETCHERS)
        ]
        try:
            await self._apply_chunks(snapshot)
            state = self.state_provider.state(snapshot.height)
            commit = self.state_provider.commit(snapshot.height)
            self._verify_app(state)
            return state, commit
        finally:
            self._chunk_queue.close()
            self._chunk_queue = None
            for t in fetchers:
                t.cancel()
            for t in fetchers:
                try:
                    await t
                except (asyncio.CancelledError, Exception):
                    pass

    # -- chunk fetching --------------------------------------------------
    async def _fetch_loop(self, snapshot: Snapshot) -> None:
        q = self._chunk_queue
        while not q.done():
            index = q.allocate()
            if index is None:
                await asyncio.sleep(0.05)
                continue
            peers = self.pool.get_peers(snapshot)
            if not peers:
                self.pool.reject(snapshot)
                q.close()
                return
            peer = peers[index % len(peers)]
            await self.request_chunk(peer, snapshot, index)
            deadline = asyncio.get_running_loop().time() + self.chunk_timeout
            while not q.has(index) and index >= q._next:
                if asyncio.get_running_loop().time() > deadline:
                    # timed out: release the allocation so the next fetch
                    # attempt (likely another peer) can pick it up
                    q._allocated.discard(index)
                    break
                await asyncio.sleep(0.05)

    # -- chunk application ----------------------------------------------
    async def _apply_chunks(self, snapshot: Snapshot) -> None:
        q = self._chunk_queue
        r = ResponseApplySnapshotChunk.Result
        while not q.done():
            nxt = await q.next(timeout=self.chunk_timeout * (snapshot.chunks + 1))
            if nxt is None:
                self.pool.reject(snapshot)
                raise _SnapshotRejectedError
            index, chunk = nxt
            resp = self.app.apply_snapshot_chunk_sync(index, chunk, q.get_sender(index))
            # punitive verbs first (syncer.go:336-360)
            for peer in resp.reject_senders:
                self.pool.reject_peer(peer)
                q.discard_sender(peer)
            for i in resp.refetch_chunks:
                q.retry(i)
            if resp.result == r.ACCEPT:
                continue
            if resp.result == r.ABORT:
                raise SyncAbortedError("app aborted during chunk apply")
            if resp.result == r.RETRY:
                q.retry(index)
            elif resp.result == r.RETRY_SNAPSHOT:
                q.retry_all()
            elif resp.result == r.REJECT_SNAPSHOT:
                self.pool.reject(snapshot)
                raise _SnapshotRejectedError
            else:
                raise SyncAbortedError(f"unknown ApplySnapshotChunk result {resp.result}")

    # -- post-restore verification ---------------------------------------
    def _verify_app(self, state) -> None:
        """syncer.go:448 verifyApp: the restored app must report the
        trusted app hash and height."""
        from tendermint_tpu.abci.types import RequestInfo

        info = self.app.info_sync(RequestInfo())
        if info.last_block_app_hash != state.app_hash:
            raise SyncAbortedError(
                f"restored app hash {info.last_block_app_hash.hex()} != trusted "
                f"{state.app_hash.hex()}"
            )
        if info.last_block_height != state.last_block_height:
            raise SyncAbortedError(
                f"restored app height {info.last_block_height} != "
                f"{state.last_block_height}"
            )
