"""Chunk queue for one snapshot restore.

Parity: reference statesync/chunks.go (chunkQueue :31: Allocate, Add,
Next, Retry, RetryAll, Discard, GetSender).  The reference spools chunks
to temp files to bound memory; chunks here are bounded by the channel's
max message size and held in memory — the restoring app consumes them
immediately in sequential order, so at most a fetch-window of chunks is
resident at once.
"""

from __future__ import annotations

import asyncio

from tendermint_tpu.abci.types import Snapshot


class ChunkQueue:
    def __init__(self, snapshot: Snapshot):
        self.snapshot = snapshot
        self._chunks: dict[int, bytes] = {}
        self._senders: dict[int, str] = {}
        self._allocated: set[int] = set()
        self._returned: set[int] = set()  # consumed by Next
        self._next = 0
        self._event = asyncio.Event()  # pulsed when a chunk arrives
        self._closed = False

    def allocate(self) -> int | None:
        """Hand out the lowest unallocated chunk index to a fetcher."""
        for i in range(self.snapshot.chunks):
            if i not in self._allocated and i not in self._chunks:
                self._allocated.add(i)
                return i
        return None

    def add(self, index: int, chunk: bytes, sender: str) -> bool:
        if self._closed or index >= self.snapshot.chunks or index in self._chunks:
            return False
        self._chunks[index] = chunk
        self._senders[index] = sender
        self._allocated.discard(index)
        self._event.set()
        return True

    def has(self, index: int) -> bool:
        return index in self._chunks

    def get_sender(self, index: int) -> str:
        return self._senders.get(index, "")

    async def next(self, timeout: float | None = None) -> tuple[int, bytes] | None:
        """Await the next sequential chunk; None on close/timeout."""
        while not self._closed:
            if self._next in self._chunks:
                i = self._next
                self._next += 1
                self._returned.add(i)
                return i, self._chunks[i]
            self._event.clear()
            try:
                if timeout is None:
                    await self._event.wait()
                else:
                    await asyncio.wait_for(self._event.wait(), timeout)
            except asyncio.TimeoutError:
                return None
        return None

    def retry(self, index: int) -> None:
        """Make a chunk re-fetchable and rewind the apply point to it."""
        for i in range(index, self.snapshot.chunks):
            self._chunks.pop(i, None)
            self._senders.pop(i, None)
            self._allocated.discard(i)
            self._returned.discard(i)
        self._next = min(self._next, index)

    def retry_all(self) -> None:
        self.retry(0)

    def discard_sender(self, peer_id: str) -> None:
        """Drop unapplied chunks from a banned sender (chunks.go:238)."""
        for i, s in list(self._senders.items()):
            if s == peer_id and i not in self._returned:
                self._chunks.pop(i, None)
                self._senders.pop(i, None)
                self._allocated.discard(i)

    def done(self) -> bool:
        return self._next >= self.snapshot.chunks

    def close(self) -> None:
        self._closed = True
        self._event.set()
