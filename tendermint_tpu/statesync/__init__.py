from .messages import (
    ChunkRequest,
    ChunkResponse,
    SnapshotsRequest,
    SnapshotsResponse,
)
from .reactor import CHUNK_CHANNEL, SNAPSHOT_CHANNEL, StateSyncReactor
from .snapshots import SnapshotKey, SnapshotPool
from .stateprovider import LightClientStateProvider
from .syncer import SyncAbortedError, Syncer

__all__ = [
    "CHUNK_CHANNEL",
    "ChunkRequest",
    "ChunkResponse",
    "LightClientStateProvider",
    "SNAPSHOT_CHANNEL",
    "SnapshotKey",
    "SnapshotPool",
    "SnapshotsRequest",
    "SnapshotsResponse",
    "StateSyncReactor",
    "SyncAbortedError",
    "Syncer",
]
