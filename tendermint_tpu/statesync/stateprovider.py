"""State provider: builds a trusted sm.State at a snapshot height via the
light client.

Parity: reference statesync/stateprovider.go:47 (lightClientStateProvider
— AppHash/Commit/State over a light.Client with ≥2 witnesses).  The
reference pulls ConsensusParams from witness RPC endpoints; here
providers may expose ``consensus_params(height)`` (the node-backed
provider does), with the genesis params as fallback.
"""

from __future__ import annotations

from tendermint_tpu.light.client import Client, TrustOptions
from tendermint_tpu.state.state import State
from tendermint_tpu.types.params import ConsensusParams


class LightClientStateProvider:
    def __init__(
        self,
        chain_id: str,
        genesis_doc,
        providers: list,
        trust_options: TrustOptions,
        now_fn=None,
    ):
        if len(providers) < 2:
            raise ValueError("at least 2 providers are required (primary + witness)")
        self.chain_id = chain_id
        self.genesis = genesis_doc
        self.providers = list(providers)
        kwargs = {"now_fn": now_fn} if now_fn is not None else {}
        self.client = Client(
            chain_id, trust_options, providers[0], list(providers[1:]), **kwargs
        )

    def app_hash(self, height: int) -> bytes:
        """AppHash at `height` is recorded in header height+1.  Also
        probes height+2 so State() is known to be constructible — a
        snapshot too close to the chain tip fails HERE and gets rejected,
        not mid-restore (stateprovider.go:94-113)."""
        lb = self.client.verify_light_block_at_height(height + 1, self._now())
        self.client.verify_light_block_at_height(height + 2, self._now())
        return lb.header.app_hash

    def commit(self, height: int):
        lb = self.client.verify_light_block_at_height(height, self._now())
        return lb.commit

    def state(self, height: int) -> State:
        """Trusted State for bootstrapping after restoring a snapshot at
        `height` (stateprovider.go:112-160): the state as of height
        `height` having been committed, i.e. validators from
        height+1 (current) and height+2 (next)."""
        now = self._now()
        last = self.client.verify_light_block_at_height(height, now)
        cur = self.client.verify_light_block_at_height(height + 1, now)
        nxt = self.client.verify_light_block_at_height(height + 2, now)
        return State(
            chain_id=self.chain_id,
            initial_height=getattr(self.genesis, "initial_height", 1) or 1,
            last_block_height=cur.height - 1,
            last_block_id=cur.header.last_block_id,
            # time of the LAST COMMITTED block (height), not of height+1 —
            # the next real block must still satisfy time monotonicity
            last_block_time_ns=last.header.time_ns,
            validators=cur.validator_set,
            next_validators=nxt.validator_set,
            last_validators=last.validator_set,
            last_height_validators_changed=cur.height,
            consensus_params=self._params(height),
            last_height_consensus_params_changed=cur.height,
            last_results_hash=cur.header.last_results_hash,
            app_hash=cur.header.app_hash,
        )

    def _params(self, height: int) -> ConsensusParams:
        for p in self.providers:
            fn = getattr(p, "consensus_params", None)
            if fn is None:
                continue
            try:
                params = fn(height)
            except Exception:
                continue
            if params is not None:
                return params
        return self.genesis.consensus_params

    def _now(self) -> int:
        return self.client.now_fn()
