"""Statesync reactor: snapshot/chunk channels + the bootstrap entry.

Parity: reference statesync/reactor.go (channels Snapshot 0x60 / Chunk
0x61 :33-59, Receive, recentSnapshots :184, Sync :472).  Serves local
app snapshots to restoring peers and runs the Syncer for a node
bootstrapping from state sync.
"""

from __future__ import annotations

import asyncio

from tendermint_tpu.abci.types import Snapshot
from tendermint_tpu.p2p.types import ChannelDescriptor, Envelope, PeerStatus
from tendermint_tpu.utils.log import Logger, nop_logger

from .messages import (
    ChunkRequest,
    ChunkResponse,
    SnapshotsRequest,
    SnapshotsResponse,
    decode_chunk_message,
    decode_snapshot_message,
    encode_chunk_message,
    encode_snapshot_message,
)
from .syncer import Syncer

SNAPSHOT_CHANNEL = 0x60
CHUNK_CHANNEL = 0x61
RECENT_SNAPSHOTS = 10  # reactor.go:48
MAX_CHUNK_BYTES = 16 * 1024 * 1024


class StateSyncReactor:
    def __init__(
        self,
        app_snapshot_conn,
        router,
        state_provider=None,
        logger: Logger | None = None,
    ):
        self.app = app_snapshot_conn
        self.router = router
        self.logger = logger or nop_logger()
        self.snapshot_ch = router.open_channel(
            ChannelDescriptor(
                channel_id=SNAPSHOT_CHANNEL,
                priority=5,
                encode=encode_snapshot_message,
                decode=decode_snapshot_message,
                max_msg_bytes=4 * 1024 * 1024,
            )
        )
        self.chunk_ch = router.open_channel(
            ChannelDescriptor(
                channel_id=CHUNK_CHANNEL,
                priority=1,
                encode=encode_chunk_message,
                decode=decode_chunk_message,
                max_msg_bytes=MAX_CHUNK_BYTES,
            )
        )
        self.peer_updates = router.subscribe_peer_updates()
        self.syncer = Syncer(
            app_snapshot_conn,
            state_provider,
            self._request_snapshots,
            self._request_chunk,
            logger=self.logger,
        )
        self._tasks: list[asyncio.Task] = []

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._tasks = [
            loop.create_task(self._snapshot_recv_loop()),
            loop.create_task(self._chunk_recv_loop()),
            loop.create_task(self._peer_update_loop()),
        ]

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks = []

    async def sync(self, discovery_time: float = 2.0, retries: int | None = 20):
        """Run a full state sync; returns (state, commit) to bootstrap
        the node (reference reactor.go:472 + node.go startStateSync)."""
        return await self.syncer.sync_any(discovery_time, retries=retries)

    # -- outbound (syncer hooks) -----------------------------------------
    async def _request_snapshots(self) -> None:
        await self.snapshot_ch.send(
            Envelope(message=SnapshotsRequest(), broadcast=True)
        )

    async def _request_chunk(self, peer_id: str, snapshot: Snapshot, index: int) -> None:
        await self.chunk_ch.send(
            Envelope(
                message=ChunkRequest(snapshot.height, snapshot.format, index),
                to=peer_id,
            )
        )

    # -- inbound ---------------------------------------------------------
    async def _snapshot_recv_loop(self) -> None:
        while True:
            env = await self.snapshot_ch.receive()
            msg, frm = env.message, env.from_
            if isinstance(msg, SnapshotsRequest):
                for s in self._recent_snapshots():
                    await self.snapshot_ch.send(
                        Envelope(
                            message=SnapshotsResponse(
                                s.height, s.format, s.chunks, s.hash, s.metadata
                            ),
                            to=frm,
                        )
                    )
            elif isinstance(msg, SnapshotsResponse):
                self.syncer.add_snapshot(
                    frm,
                    Snapshot(msg.height, msg.format, msg.chunks, msg.hash, msg.metadata),
                )

    def _recent_snapshots(self) -> list[Snapshot]:
        try:
            snapshots = list(self.app.list_snapshots_sync())
        except Exception as e:
            self.logger.error("failed to list snapshots", err=str(e))
            return []
        snapshots.sort(key=lambda s: (s.height, s.format), reverse=True)
        return snapshots[:RECENT_SNAPSHOTS]

    async def _chunk_recv_loop(self) -> None:
        while True:
            env = await self.chunk_ch.receive()
            msg, frm = env.message, env.from_
            if isinstance(msg, ChunkRequest):
                try:
                    chunk = self.app.load_snapshot_chunk_sync(msg.height, msg.format, msg.index)
                except Exception as e:
                    self.logger.error("failed to load chunk", err=str(e))
                    chunk = None
                await self.chunk_ch.send(
                    Envelope(
                        message=ChunkResponse(
                            msg.height,
                            msg.format,
                            msg.index,
                            chunk or b"",
                            missing=chunk is None,
                        ),
                        to=frm,
                    )
                )
            elif isinstance(msg, ChunkResponse):
                if not msg.missing:
                    self.syncer.add_chunk(
                        frm, msg.height, msg.format, msg.index, msg.chunk
                    )

    async def _peer_update_loop(self) -> None:
        while True:
            update = await self.peer_updates.get()
            if update.status == PeerStatus.DOWN:
                self.syncer.remove_peer(update.node_id)
