"""Snapshot pool: advertised snapshots ranked for restore attempts.

Parity: reference statesync/snapshots.go (snapshotPool :45, Add :136,
Best :176, Reject/RejectFormat/RejectPeer, GetPeers).  Ranking: height
desc, format desc, number of advertising peers desc.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from tendermint_tpu.abci.types import Snapshot


@dataclass(frozen=True)
class SnapshotKey:
    height: int
    format: int
    chunks: int
    hash: bytes


def _key(s: Snapshot) -> SnapshotKey:
    return SnapshotKey(s.height, s.format, s.chunks, s.hash)


@dataclass
class _Entry:
    snapshot: Snapshot
    peers: set = field(default_factory=set)


class SnapshotPool:
    def __init__(self):
        self._entries: dict[SnapshotKey, _Entry] = {}
        self._rejected_keys: set[SnapshotKey] = set()
        self._rejected_formats: set[int] = set()
        self._rejected_peers: set[str] = set()

    def add(self, peer_id: str, snapshot: Snapshot) -> bool:
        """Returns True if this (snapshot, peer) pair is new."""
        key = _key(snapshot)
        if (
            key in self._rejected_keys
            or snapshot.format in self._rejected_formats
            or peer_id in self._rejected_peers
        ):
            return False
        e = self._entries.get(key)
        if e is None:
            e = self._entries[key] = _Entry(snapshot)
        if peer_id in e.peers:
            return False
        e.peers.add(peer_id)
        return True

    def best(self) -> Snapshot | None:
        ranked = self.ranked()
        return ranked[0] if ranked else None

    def ranked(self) -> list[Snapshot]:
        entries = [e for e in self._entries.values() if e.peers]
        entries.sort(
            key=lambda e: (e.snapshot.height, e.snapshot.format, len(e.peers)),
            reverse=True,
        )
        return [e.snapshot for e in entries]

    def get_peers(self, snapshot: Snapshot) -> list[str]:
        e = self._entries.get(_key(snapshot))
        return sorted(e.peers) if e else []

    def reject(self, snapshot: Snapshot) -> None:
        key = _key(snapshot)
        self._rejected_keys.add(key)
        self._entries.pop(key, None)

    def reject_format(self, format: int) -> None:
        self._rejected_formats.add(format)
        for key in [k for k in self._entries if k.format == format]:
            del self._entries[key]

    def reject_peer(self, peer_id: str) -> None:
        self._rejected_peers.add(peer_id)
        self.remove_peer(peer_id)

    def remove_peer(self, peer_id: str) -> None:
        for key in list(self._entries):
            e = self._entries[key]
            e.peers.discard(peer_id)
            if not e.peers:
                del self._entries[key]
