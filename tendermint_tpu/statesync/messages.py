"""Statesync wire messages (reference proto/tendermint/statesync/types.proto,
statesync/messages.go)."""

from __future__ import annotations

from dataclasses import dataclass

from tendermint_tpu.wire.proto import guard_decode, ProtoWriter, fields_to_dict, to_int64


@dataclass
class SnapshotsRequest:
    def encode(self) -> bytes:
        return b""

    @classmethod
    def decode(cls, data: bytes) -> "SnapshotsRequest":  # noqa: ARG003
        return cls()


@dataclass
class SnapshotsResponse:
    """One advertised snapshot (height=1, format=2, chunks=3, hash=4,
    metadata=5)."""

    height: int
    format: int
    chunks: int
    hash: bytes
    metadata: bytes = b""

    def encode(self) -> bytes:
        return (
            ProtoWriter()
            .varint(1, self.height)
            .varint(2, self.format)
            .varint(3, self.chunks)
            .bytes_(4, self.hash)
            .bytes_(5, self.metadata)
            .bytes_out()
        )

    @classmethod
    def decode(cls, data: bytes) -> "SnapshotsResponse":
        f = fields_to_dict(data)
        return cls(
            height=to_int64(f.get(1, [0])[0]),
            format=f.get(2, [0])[0],
            chunks=f.get(3, [0])[0],
            hash=f.get(4, [b""])[0],
            metadata=f.get(5, [b""])[0],
        )


@dataclass
class ChunkRequest:
    """height=1, format=2, index=3."""

    height: int
    format: int
    index: int

    def encode(self) -> bytes:
        return (
            ProtoWriter()
            .varint(1, self.height)
            .varint(2, self.format)
            .varint(3, self.index)
            .bytes_out()
        )

    @classmethod
    def decode(cls, data: bytes) -> "ChunkRequest":
        f = fields_to_dict(data)
        return cls(to_int64(f.get(1, [0])[0]), f.get(2, [0])[0], f.get(3, [0])[0])


@dataclass
class ChunkResponse:
    """height=1, format=2, index=3, chunk=4, missing=5."""

    height: int
    format: int
    index: int
    chunk: bytes = b""
    missing: bool = False

    def encode(self) -> bytes:
        return (
            ProtoWriter()
            .varint(1, self.height)
            .varint(2, self.format)
            .varint(3, self.index)
            .bytes_(4, self.chunk)
            .bool_(5, self.missing)
            .bytes_out()
        )

    @classmethod
    def decode(cls, data: bytes) -> "ChunkResponse":
        f = fields_to_dict(data)
        return cls(
            height=to_int64(f.get(1, [0])[0]),
            format=f.get(2, [0])[0],
            index=f.get(3, [0])[0],
            chunk=f.get(4, [b""])[0],
            missing=bool(f.get(5, [0])[0]),
        )


_SNAPSHOT_TYPES: list[type] = [SnapshotsRequest, SnapshotsResponse]
_CHUNK_TYPES: list[type] = [ChunkRequest, ChunkResponse]


def _encode(msg, types: list[type]) -> bytes:
    fld = types.index(type(msg)) + 1
    return ProtoWriter().message(fld, msg.encode(), always=True).bytes_out()


def _decode(data: bytes, types: list[type]):
    f = fields_to_dict(data)
    for i, t in enumerate(types):
        if i + 1 in f:
            return t.decode(f[i + 1][0])
    raise ValueError("unknown statesync message")


def encode_snapshot_message(msg) -> bytes:
    return _encode(msg, _SNAPSHOT_TYPES)


@guard_decode
def decode_snapshot_message(data: bytes):
    return _decode(data, _SNAPSHOT_TYPES)


def encode_chunk_message(msg) -> bytes:
    return _encode(msg, _CHUNK_TYPES)


@guard_decode
def decode_chunk_message(data: bytes):
    return _decode(data, _CHUNK_TYPES)
