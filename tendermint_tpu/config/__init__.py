from .config import (
    BaseConfig,
    Config,
    InstrumentationConfig,
    P2PConfig,
    RPCConfig,
    StateSyncConfig,
    TxIndexConfig,
    default_config,
    load_config,
    test_config,
    write_config,
)

__all__ = [
    "BaseConfig",
    "Config",
    "InstrumentationConfig",
    "P2PConfig",
    "RPCConfig",
    "StateSyncConfig",
    "TxIndexConfig",
    "default_config",
    "load_config",
    "test_config",
    "write_config",
]
