"""Config aggregate + TOML persistence + home-dir layout.

Parity: reference config/config.go:55-1070 (Config{Base, RPC, P2P,
Mempool, StateSync, FastSync, Consensus, TxIndex, Instrumentation} with
Default*/Test* constructors and ValidateBasic) and config/toml.go
(config.toml rendering; reads use stdlib tomllib instead of viper).

Home-dir layout (reference: cmd/tendermint/commands/init.go):
    <home>/config/config.toml
    <home>/config/genesis.json
    <home>/config/node_key.json
    <home>/config/priv_validator_key.json
    <home>/data/priv_validator_state.json
    <home>/data/*.db, <home>/data/cs.wal
"""

from __future__ import annotations

import dataclasses
import os
try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: same API from the backport
    try:
        import tomli as tomllib
    except ModuleNotFoundError:  # neither: raise at load_config, not here
        tomllib = None
from dataclasses import dataclass, field

from tendermint_tpu.consensus.config import ConsensusConfig
from tendermint_tpu.mempool.mempool import MempoolConfig


@dataclass
class BaseConfig:
    chain_id: str = ""  # loaded from genesis
    moniker: str = "tpu-node"
    fast_sync: bool = True
    db_backend: str = "sqlite"  # sqlite | memdb | native (C++ backend when built)
    log_level: str = "info"
    log_format: str = "plain"  # plain | json
    genesis_file: str = "config/genesis.json"
    priv_validator_key_file: str = "config/priv_validator_key.json"
    priv_validator_state_file: str = "data/priv_validator_state.json"
    priv_validator_laddr: str = ""  # remote signer listen address
    node_key_file: str = "config/node_key.json"
    abci: str = "builtin"  # builtin | socket
    proxy_app: str = "kvstore"  # app name (builtin) or address (socket)
    snapshot_interval: int = 0  # builtin-app snapshots every N heights (statesync serving)
    filter_peers: bool = False


@dataclass
class RPCConfig:
    laddr: str = "tcp://127.0.0.1:26657"
    grpc_laddr: str = ""  # optional gRPC broadcast API (reference GRPCListenAddress)
    cors_allowed_origins: list[str] = field(default_factory=list)
    max_open_connections: int = 900
    max_subscription_clients: int = 100
    max_subscriptions_per_client: int = 5
    timeout_broadcast_tx_commit_ms: int = 10_000
    max_body_bytes: int = 1_000_000
    pprof_laddr: str = ""
    # expose the unsafe control routes (dial_seeds, dial_peers,
    # unsafe_flush_mempool) — reference config.RPC.Unsafe / routes.go:51-56
    unsafe: bool = False


@dataclass
class P2PConfig:
    transport: str = "tcp"  # "tcp" (SecretConnection over sockets) | "memory"
    laddr: str = "tcp://0.0.0.0:26656"
    external_address: str = ""
    seeds: str = ""  # comma-separated NodeID@host:port
    persistent_peers: str = ""
    max_num_inbound_peers: int = 40
    max_num_outbound_peers: int = 10
    pex: bool = True
    seed_mode: bool = False
    private_peer_ids: str = ""
    handshake_timeout_s: int = 20
    dial_timeout_s: int = 3
    send_rate: int = 5_120_000
    recv_rate: int = 5_120_000
    # keepalive (reference p2p/conn/connection.go:47-48): ping every
    # ping_interval_s; evict a peer silent for pong_timeout_s after a
    # ping.  ping_interval_s = 0 disables keepalive.
    ping_interval_s: float = 60.0
    pong_timeout_s: float = 45.0
    addr_book_file: str = "config/addrbook.json"
    addr_book_strict: bool = True


@dataclass
class StateSyncConfig:
    enable: bool = False
    rpc_servers: list[str] = field(default_factory=list)
    trust_height: int = 0
    trust_hash: str = ""
    trust_period_s: int = 168 * 3600  # 1 week
    discovery_time_s: float = 15.0
    chunk_request_timeout_s: float = 10.0


@dataclass
class BlockSyncConfig:
    version: str = "v0"


@dataclass
class TxIndexConfig:
    indexer: str = "kv"  # kv | null


@dataclass
class InstrumentationConfig:
    prometheus: bool = False
    prometheus_listen_addr: str = ":26660"
    namespace: str = "tendermint"


@dataclass
class Config:
    home: str = "."
    base: BaseConfig = field(default_factory=BaseConfig)
    rpc: RPCConfig = field(default_factory=RPCConfig)
    p2p: P2PConfig = field(default_factory=P2PConfig)
    mempool: MempoolConfig = field(default_factory=MempoolConfig)
    statesync: StateSyncConfig = field(default_factory=StateSyncConfig)
    blocksync: BlockSyncConfig = field(default_factory=BlockSyncConfig)
    consensus: ConsensusConfig = field(default_factory=ConsensusConfig)
    tx_index: TxIndexConfig = field(default_factory=TxIndexConfig)
    instrumentation: InstrumentationConfig = field(default_factory=InstrumentationConfig)

    # -- paths -----------------------------------------------------------
    def path(self, rel: str) -> str:
        return rel if os.path.isabs(rel) else os.path.join(self.home, rel)

    @property
    def genesis_file(self) -> str:
        return self.path(self.base.genesis_file)

    @property
    def node_key_file(self) -> str:
        return self.path(self.base.node_key_file)

    @property
    def priv_validator_key_file(self) -> str:
        return self.path(self.base.priv_validator_key_file)

    @property
    def priv_validator_state_file(self) -> str:
        return self.path(self.base.priv_validator_state_file)

    @property
    def db_dir(self) -> str:
        return self.path("data")

    @property
    def wal_file(self) -> str:
        return self.path("data/cs.wal")

    @property
    def config_file(self) -> str:
        return self.path("config/config.toml")

    @property
    def addr_book_file(self) -> str:
        return self.path(self.p2p.addr_book_file)

    def ensure_dirs(self) -> None:
        for d in ("config", "data"):
            os.makedirs(self.path(d), exist_ok=True)

    # -- validation ------------------------------------------------------
    def validate_basic(self) -> None:
        if self.base.db_backend not in ("sqlite", "memdb", "native"):
            raise ValueError(f"unknown db_backend {self.base.db_backend!r}")
        if self.tx_index.indexer not in ("kv", "null"):
            raise ValueError(f"unknown indexer {self.tx_index.indexer!r}")
        if self.blocksync.version not in ("v0",):
            raise ValueError(f"unknown blocksync version {self.blocksync.version!r}")
        if self.consensus.timeout_commit_ms < 0:
            raise ValueError("timeout_commit_ms must be >= 0")
        if self.mempool.size <= 0:
            raise ValueError("mempool size must be positive")
        if self.statesync.enable:
            if len(self.statesync.rpc_servers) < 2:
                raise ValueError("statesync requires >= 2 rpc_servers")
            if self.statesync.trust_height <= 0 or not self.statesync.trust_hash:
                raise ValueError("statesync requires trust_height and trust_hash")


_SECTIONS = [
    ("base", BaseConfig),
    ("rpc", RPCConfig),
    ("p2p", P2PConfig),
    ("mempool", MempoolConfig),
    ("statesync", StateSyncConfig),
    ("blocksync", BlockSyncConfig),
    ("consensus", ConsensusConfig),
    ("tx_index", TxIndexConfig),
    ("instrumentation", InstrumentationConfig),
]


def default_config(home: str = ".") -> Config:
    return Config(home=home)


def test_config(home: str = ".") -> Config:
    cfg = Config(home=home, consensus=ConsensusConfig.test_config())
    cfg.base.db_backend = "memdb"
    cfg.p2p.addr_book_strict = False
    cfg.p2p.transport = "memory"  # in-proc tests default to the fake net
    cfg.rpc.laddr = "tcp://127.0.0.1:0"  # ephemeral port; no collisions
    return cfg


def _toml_value(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return str(v)
    if isinstance(v, str):
        return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'
    if isinstance(v, list):
        return "[" + ", ".join(_toml_value(x) for x in v) + "]"
    raise TypeError(f"unsupported TOML value {v!r}")


def write_config(cfg: Config, path: str | None = None) -> str:
    """Render and write config.toml; returns the rendered text."""
    lines = ["# tendermint_tpu configuration\n"]
    for name, _ in _SECTIONS:
        section = getattr(cfg, name)
        lines.append(f"[{name}]")
        for f in dataclasses.fields(section):
            lines.append(f"{f.name} = {_toml_value(getattr(section, f.name))}")
        lines.append("")
    text = "\n".join(lines)
    if path is None:
        path = cfg.config_file
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as fh:
        fh.write(text)
    return text


def load_config(home: str) -> Config:
    """Load <home>/config/config.toml over defaults; unknown keys are
    ignored (forward compatibility, like viper)."""
    cfg = Config(home=home)
    path = cfg.config_file
    if not os.path.exists(path):
        return cfg
    if tomllib is None:
        raise ImportError(
            "reading config.toml requires tomllib (Python >= 3.11) or the "
            "`tomli` backport; neither is installed")
    with open(path, "rb") as fh:
        doc = tomllib.load(fh)
    for name, cls in _SECTIONS:
        data = doc.get(name)
        if not isinstance(data, dict):
            continue
        section = getattr(cfg, name)
        valid = {f.name for f in dataclasses.fields(cls)}
        for k, v in data.items():
            if k in valid:
                setattr(section, k, v)
    return cfg
