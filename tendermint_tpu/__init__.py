"""tendermint_tpu — a TPU-native BFT state-machine-replication engine.

A ground-up rebuild of the Tendermint Core capability set (reference:
yayajacky/tendermint, pure Go) designed TPU-first: the crypto data plane
(batch Ed25519 verification, hashing) runs as JAX/XLA programs on device,
sharded over a `jax.sharding.Mesh` for large validator sets, while the
host runtime (consensus FSM, gossip, stores) is an asyncio actor system
replacing the reference's goroutine architecture.
"""

__version__ = "0.1.0"
