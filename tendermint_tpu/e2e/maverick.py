"""Maverick: a consensus state machine with pluggable per-height
misbehaviors for byzantine testing.

Parity: reference test/maverick/consensus/misbehavior.go — hooks at
EnterPrevote/EnterPrecommit etc., selectable per height from the e2e
manifest (`misbehaviors` map).  Here the hooks are methods on a
ConsensusState subclass; the misbehavior map is {height: name}.

Misbehaviors:
  * "double-prevote": emit the honest prevote AND a conflicting prevote
    for a fabricated block, signed with the raw validator key (bypassing
    the privval double-sign guard — that guard is the node protecting
    itself; a real byzantine actor has the key).
  * "double-precommit": the same equivocation at the precommit step.
  * "amnesia": forget the lock when prevoting — vote for the current
    proposal even while locked on a different block (the amnesia attack;
    honest peers must stay safe because their own locks hold).
  * "nil-prevote": prevote nil regardless of the proposal.
  * "nil-precommit": precommit nil regardless of the polka.
  * "ignore-proposal": drop every proposal received at the height — the
    receive-side hook (reference misbehavior.go ReceiveProposal, the 6th
    hook point of its Misbehavior struct); the maverick never completes
    the proposal, prevotes nil, and the honest majority must keep
    committing without it.
"""

from __future__ import annotations

from tendermint_tpu.consensus.messages import VoteMessage
from tendermint_tpu.consensus.state import ConsensusState
from tendermint_tpu.types import Vote
from tendermint_tpu.types.basic import BlockID, PartSetHeader, SignedMsgType

MISBEHAVIORS = (
    "double-prevote",
    "double-precommit",
    "amnesia",
    "nil-prevote",
    "nil-precommit",
    "ignore-proposal",
)


class MaverickConsensusState(ConsensusState):
    def __init__(self, *args, misbehaviors: dict[int, str] | None = None,
                 raw_key=None, **kw):
        super().__init__(*args, **kw)
        self.misbehaviors = misbehaviors or {}
        self.raw_key = raw_key
        # Set by the node/reactor wiring: sends a vote straight to peers,
        # bypassing our own vote set (which would reject the conflict —
        # a node never gossips votes it knows to be equivocating; the
        # reference maverick reactor broadcasts directly too).
        self.broadcast_vote = None
        self.amnesia_prevotes = 0  # diagnostics: times the lock was ignored
        self.ignored_proposals = 0  # diagnostics: proposals dropped
        for h, name in self.misbehaviors.items():
            if name not in MISBEHAVIORS:
                raise ValueError(f"unknown misbehavior {name!r} at height {h}")

    def _active(self) -> str | None:
        return self.misbehaviors.get(self.rs.height)

    def set_proposal(self, proposal, peer_id: str = "") -> None:
        if self._active() == "ignore-proposal":
            self.ignored_proposals += 1
            self.logger.info("maverick: dropping received proposal",
                             height=self.rs.height, round=self.rs.round)
            return
        super().set_proposal(proposal, peer_id)

    def do_prevote(self, height: int, round_: int) -> None:
        if self._active() == "amnesia" and self.rs.proposal_block is not None:
            # forget the lock: vote for whatever is proposed NOW
            if (
                self.rs.locked_block is not None
                and self.rs.locked_block.hash() != self.rs.proposal_block.hash()
            ):
                self.amnesia_prevotes += 1  # an actual lock contradiction
            self.sign_add_vote(
                SignedMsgType.PREVOTE,
                self.rs.proposal_block.hash(),
                self.rs.proposal_block_parts.header(),
            )
            self.logger.info("maverick: amnesia prevote", height=height,
                             round=round_)
            return
        super().do_prevote(height, round_)

    def sign_add_vote(self, msg_type: SignedMsgType, hash_, header) -> Vote | None:
        mis = self._active()
        if mis == "nil-prevote" and msg_type == SignedMsgType.PREVOTE:
            hash_, header = b"", PartSetHeader(0, b"")
        if mis == "nil-precommit" and msg_type == SignedMsgType.PRECOMMIT:
            hash_, header = b"", PartSetHeader(0, b"")
        vote = super().sign_add_vote(msg_type, hash_, header)
        equivocate = (
            (mis == "double-prevote" and msg_type == SignedMsgType.PREVOTE)
            or (mis == "double-precommit" and msg_type == SignedMsgType.PRECOMMIT)
        )
        if equivocate and vote is not None and self.raw_key is not None:
            # conflicting vote for a fabricated block at the same H/R,
            # signed directly with the raw key (reference maverick
            # double-prevote, extended to the precommit step)
            evil = Vote(
                type=msg_type,
                height=vote.height,
                round=vote.round,
                block_id=BlockID(hash=b"\xde" * 32,
                                 part_set_header=PartSetHeader(1, b"\xad" * 32)),
                timestamp_ns=vote.timestamp_ns,
                validator_address=vote.validator_address,
                validator_index=vote.validator_index,
            )
            evil.signature = self.raw_key.sign(evil.sign_bytes(self.state.chain_id))
            if self.broadcast_vote is not None:
                self.broadcast_vote(evil)
            self.logger.info("maverick: equivocating vote emitted",
                             type=msg_type.name, height=vote.height,
                             round=vote.round)
        return vote
