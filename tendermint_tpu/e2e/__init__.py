"""End-to-end harness: manifest-driven multi-process testnets, byzantine
(maverick) consensus variants, load generation, perturbations, and
invariant checks (reference test/e2e/ + test/maverick/)."""
