"""Randomized e2e manifest generator.

Parity: reference test/e2e/generator/ — explores the testnet config
space with a seeded RNG so nightly runs cover combinations no hand-
written manifest would, while staying reproducible: the same seed
always yields the same manifest list.

Dimensions (each citing the reference generator's equivalent in
test/e2e/generator/generate.go testnetCombinations):
  validators / target_height / load_rate   — topology + load
  perturb (kill/pause/restart)             — perturbations
  misbehaviors (all 6 maverick hooks)      — misbehaviors
  abci builtin/socket/unix/grpc            — ABCIProtocol (r5: unix — TSP
                                             over AF_UNIX, abci/socket.py)
  db_backend sqlite/native/memdb           — database (config_overrides)
  statesync_join                           — state_sync node mode
  key_type ed25519/secp256k1               — KeyType (r4: secp256k1 is a
                                             first-class consensus key)

Not covered (audited waivers): sr25519 validator keys (no vetted
schnorrkel implementation in-image — PARITY.md) and per-node version
mixing (single binary).
"""

from __future__ import annotations

import random

PERTURB_OPS = ("kill", "pause", "restart")  # reference perturb.go:29-66
# the maverick's FULL misbehavior menu (e2e/maverick.py MISBEHAVIORS)
MISBEHAVIORS = (
    "double-prevote",
    "double-precommit",
    "amnesia",
    "nil-prevote",
    "nil-precommit",
    "ignore-proposal",
)
ABCI_MODES = ("builtin", "builtin", "socket", "unix", "grpc")  # weighted in-proc
DB_BACKENDS = ("sqlite", "sqlite", "native", "memdb")


def generate_manifest(rng: random.Random, index: int = 0) -> dict:
    """One random manifest (reference generate.go Generate)."""
    n_vals = rng.choice((2, 4, 4, 5))  # weighted toward the canonical 4
    target = rng.randint(6, 10)
    abci = rng.choice(ABCI_MODES)
    db = rng.choice(DB_BACKENDS)
    manifest: dict = {
        "chain_id": f"gen-{index}",
        "validators": n_vals,
        "target_height": target,
        "load_rate": rng.choice((0, 5, 10)),
        # disjoint port range per manifest: a sweep runs nets back to
        # back, and recycling one base port made lingering sockets from
        # manifest N wedge manifest N+1 (each net needs 2 ports/node
        # plus n app-server ports for socket/grpc abci, all inside the
        # 24-port slice: offsets 0..3n-1, n <= 5)
        "base_port": 28000 + (index % 64) * 24,
    }
    overrides: dict = {}
    if abci != "builtin":
        manifest["abci"] = abci
    if db != "sqlite":
        overrides["base.db_backend"] = db
    # validator key type (reference manifest KeyType): secp256k1 nets
    # exercise the non-batched verify routing end to end
    if rng.random() < 0.2:
        manifest["key_type"] = "secp256k1"

    # statesync join: the last validator sits out, then joins the live
    # net via snapshot restore.  Needs >=4 validators so the remaining
    # supermajority keeps committing, and snapshot serving enabled.
    statesync_join = n_vals >= 4 and db != "memdb" and rng.random() < 0.25
    if statesync_join:
        manifest["statesync_join"] = True
        overrides["base.snapshot_interval"] = 4
        manifest["target_height"] = target = max(target, 10)

    # perturbations: up to 2, never on node 0 (the RPC anchor the runner
    # uses for invariant checks) and never on the statesync joiner, at
    # heights the net will actually reach.  memdb keeps only "pause": a
    # killed memdb node restarts empty and re-syncs from genesis, which
    # blows the sweep's time budget without adding coverage beyond the
    # dedicated blocksync tests.
    ops = ("pause",) if db == "memdb" else PERTURB_OPS
    hi_node = n_vals - 1 if statesync_join else n_vals
    perturb = []
    for _ in range(rng.randint(0, 2)):
        if hi_node <= 1:
            break
        perturb.append({
            "node": rng.randrange(1, hi_node),
            "op": rng.choice(ops),
            "at_height": rng.randint(2, max(2, target - 3)),
        })
    if perturb:
        manifest["perturb"] = perturb

    # byzantine: at most one maverick (reference e2e manifests mark a
    # single misbehaving node per net), only with >= 4 validators so the
    # honest supermajority keeps the chain live.  NEVER combined with
    # statesync_join: the joiner is an ABSENT validator until well past
    # 2*snapshot_interval, so maverick + joiner = 2 faults, over the
    # BFT budget floor((n-1)/3) for every n < 7 — seed-42's gen-8 wedged
    # permanently at the maverick height (3/5 prevotes < 2/3 with the
    # joiner gated on a height the net could no longer reach).
    if n_vals >= 4 and not statesync_join and rng.random() < 0.5:
        node = rng.randrange(1, hi_node)
        height = rng.randint(2, max(2, target - 3))
        manifest["misbehaviors"] = {str(node): {str(height): rng.choice(MISBEHAVIORS)}}

    if overrides:
        manifest["config_overrides"] = overrides
    return manifest


def generate(seed: int, n: int = 8) -> list[dict]:
    """Reproducible manifest list for a nightly sweep."""
    rng = random.Random(seed)
    return [generate_manifest(rng, i) for i in range(n)]


def generate_simnet(seed: int, n: int = 4):
    """Simnet mode: reproducible in-process fault scenarios instead of
    subprocess manifests — same seeded-exploration contract, but the
    dimensions are the fault menu (partitions, slow links, drops,
    crash-restart with WAL replay, mavericks) over 8-24 node nets with
    up to thousands of validator slots (simnet/scenario.py)."""
    from tendermint_tpu.simnet.scenario import generate as _generate

    return _generate(seed, n)
