"""Randomized e2e manifest generator.

Parity: reference test/e2e/generator/ — explores the testnet config
space with a seeded RNG so nightly runs cover combinations no hand-
written manifest would (validator counts, load rates, perturbation
schedules, byzantine misbehaviors), while staying reproducible: the
same seed always yields the same manifest list.

The config space is the subset this framework's runner supports
(tendermint_tpu/e2e/runner.py manifest schema); each knob cites the
reference generator's equivalent dimension (test/e2e/generator/
generate.go: testnetCombinations, nodeVersions/perturbations).
"""

from __future__ import annotations

import random

PERTURB_OPS = ("kill", "pause", "restart")  # reference perturb.go:29-66
# the maverick's full misbehavior menu (e2e/maverick.py); the generator
# draws equivocations and amnesia — nil-voting is just liveness noise
MISBEHAVIORS = ("double-prevote", "double-precommit", "amnesia")


def generate_manifest(rng: random.Random, index: int = 0) -> dict:
    """One random manifest (reference generate.go Generate)."""
    n_vals = rng.choice((2, 4, 4, 5))  # weighted toward the canonical 4
    target = rng.randint(6, 10)
    manifest: dict = {
        "chain_id": f"gen-{index}",
        "validators": n_vals,
        "target_height": target,
        "load_rate": rng.choice((0, 5, 10)),
        # disjoint port range per manifest: a sweep runs nets back to
        # back, and recycling one base port made lingering sockets from
        # manifest N wedge manifest N+1 (each net needs 2 ports/node)
        "base_port": 28000 + (index % 64) * 24,
    }

    # perturbations: up to 2, never on node 0 (the RPC anchor the runner
    # uses for invariant checks), at heights the net will actually reach
    perturb = []
    for _ in range(rng.randint(0, 2)):
        perturb.append({
            "node": rng.randrange(1, n_vals),
            "op": rng.choice(PERTURB_OPS),
            "at_height": rng.randint(2, max(2, target - 3)),
        })
    if perturb:
        manifest["perturb"] = perturb

    # byzantine: at most one maverick (reference e2e manifests mark a
    # single misbehaving node per net), only with >= 4 validators so the
    # honest supermajority keeps the chain live
    if n_vals >= 4 and rng.random() < 0.5:
        node = rng.randrange(1, n_vals)
        height = rng.randint(2, max(2, target - 3))
        manifest["misbehaviors"] = {str(node): {str(height): rng.choice(MISBEHAVIORS)}}

    return manifest


def generate(seed: int, n: int = 8) -> list[dict]:
    """Reproducible manifest list for a nightly sweep."""
    rng = random.Random(seed)
    return [generate_manifest(rng, i) for i in range(n)]
