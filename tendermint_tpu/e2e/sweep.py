"""Nightly-style e2e sweep: run a batch of generated manifests.

Parity: reference .github/workflows/e2e-nightly.yml + test/e2e/generator
— the randomized-config testnet sweep.  Each manifest gets a fresh
temp dir; results are printed per manifest and the exit code is the
failure count.

    python -m tendermint_tpu.e2e.sweep --seed 7 --n 4
"""

from __future__ import annotations

import argparse
import asyncio
import shutil
import sys
import tempfile
import traceback

from tendermint_tpu.e2e.generator import generate
from tendermint_tpu.e2e.runner import Testnet


async def run_manifest(manifest: dict, root: str, timeout: float = 300.0) -> None:
    net = Testnet(manifest, root)
    net.setup()
    net.start()
    try:
        target = manifest["target_height"]
        # with statesync_join the last validator starts OFFLINE and
        # joins mid-run; height waits track the initially-live nodes
        live = [n for n in net.nodes if n.proc is not None]
        # perturbations fire at their scheduled heights while the net
        # climbs toward the target (reference runner: Perturb between
        # Load and Test) — run them concurrently with the height wait
        perturb_task = asyncio.ensure_future(net.run_perturbations(timeout=timeout))
        try:
            if manifest.get("statesync_join"):
                await net.run_statesync_join(timeout=timeout)
            await net.wait_for_height(target, nodes=live, timeout=timeout)
            await asyncio.wait_for(perturb_task, timeout=timeout)
        finally:
            if not perturb_task.done():
                perturb_task.cancel()
                try:
                    await perturb_task
                except asyncio.CancelledError:
                    pass
            elif not perturb_task.cancelled() and perturb_task.exception():
                # a perturbation failure is the root cause — don't let a
                # later height-wait timeout shadow it (and don't leave an
                # unretrieved task exception)
                raise perturb_task.exception()
        if manifest.get("load_rate"):
            await net.load(total_txs=min(10, manifest["load_rate"] * 2),
                           rate=manifest["load_rate"])
        net.check_blocks_identical(target)
        net.check_app_hashes_agree()
    finally:
        net.stop()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--n", type=int, default=4)
    ap.add_argument("--timeout", type=float, default=300.0)
    ap.add_argument("--keep", action="store_true", help="keep testnet dirs")
    args = ap.parse_args(argv)

    manifests = generate(args.seed, args.n)
    failures = 0
    for i, m in enumerate(manifests):
        root = tempfile.mkdtemp(prefix=f"tmtpu-sweep-{i}-")
        label = (f"[{i + 1}/{len(manifests)}] {m['chain_id']}: "
                 f"{m['validators']} vals, target {m['target_height']}, "
                 f"perturb={len(m.get('perturb', []))}, "
                 f"byzantine={'yes' if m.get('misbehaviors') else 'no'}")
        try:
            asyncio.run(run_manifest(m, root, timeout=args.timeout))
            print(f"PASS {label}")
        except Exception:
            failures += 1
            print(f"FAIL {label}")
            traceback.print_exc()
        finally:
            if not args.keep:
                shutil.rmtree(root, ignore_errors=True)
    print(f"{len(manifests) - failures}/{len(manifests)} manifests passed")
    return failures


if __name__ == "__main__":
    sys.exit(main())
